"""SGEMM: C_out = alpha * A @ B + beta * C  (SURVEY.md C5).

Reference config: 1024x1024x1024 float32 (BASELINE.json configs[1]).
Metric of record: GFLOPS/chip = 2*M*N*K / t (BASELINE.md).

TPU design: MXU-tiled Pallas matmul. Grid is (M/bm, N/bn, K/bk) with the
K dimension innermost (sequential on TPU), accumulating partial products
into a float32 VMEM scratch block and committing alpha*acc + beta*C on
the final K step. Blocks default to tall-K tiles (bm,bn,bk) =
(256, N up to 2048, 1024) — measured at the bf16_3x compute ceiling,
see docs/PERF.md — and every matmul is a multiple of the 128x128
systolic array.

MXU precision: fp32 matmuls are emulated on the bf16 systolic array by
multi-pass splitting. Default is 'high' (bf16_3x): measured 60-64 vs
29.8 TFLOPS for 'float32' (bf16_6x) at 1024^3 on v5 lite. Worst-case
rel error of the 3x split is ~3e-4 (the dropped lo@lo term; typical
elements land ~1e-5) — the C golden checker's acceptance bar
(rtol 1e-3 + atol 1e-3, c/sgemm.c) keeps >3x margin over that at
every element magnitude, analogous to CUDA SGEMM on TF32 tensor
cores. Set
TPK_SGEMM_PRECISION=float32 (or pass precision=) for fp32-faithful
accumulation (rtol 2e-5 contract) at half the speed. Caveat shared by
every bf16-split scheme (including XLA's): inputs with |x| > bf16 max
(~3.39e38) overflow the hi part and yield inf/NaN.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

# drift-prone Pallas names resolve through the compat choke point
# (tpukernels/compat.py): this env may ship pltpu.TPUCompilerParams
# (jax 0.4.x) where the code was written against CompilerParams
from tpukernels.compat import CompilerParams, pl, pltpu
from tpukernels.tuning import SearchSpace, Tunable, resolve
from tpukernels.utils import cdiv, default_interpret


def _vmem_bytes(params, shape=None):
    """Analytic VMEM need of a (bm, bn, bk) tile PREFERENCE — the
    32 MiB arithmetic the old tools/sgemm_tune.py documented in prose,
    now the search space's feasibility filter. The per-block byte
    components are the SHARED arithmetic in
    ``tuning/roofline.py.sgemm_bytes_per_block`` (the roofline's HBM
    byte count derives from the same helper — one formula, two
    consumers).

    Model (bf16_3x, the config of record): the K-streamed A and B
    hi/lo bf16 block pairs are multiple-buffered — x2 on the default
    BlockSpec-pipelined path (Pallas double-buffers; ``depth`` 1) and
    x``depth`` on the manual ping-pong DMA path (depth 2/3) — while
    the C/out f32 blocks and the f32 accumulator scratch count once:

        buf*(4*bm*bk + 4*bk*bn)  (A + B hi/lo pairs, buffered)
        + 12*bm*bn               (C + out + acc)

    Control (256, 2048, 1024, depth 1) = 24 MiB inside the 32 MiB
    budget; bn=2048 with bk=2048 puts the B pair alone at 32 MiB — the
    combination the old tuner grid documented as infeasible (and
    depth=3 at the control blocks lands at ~34.6 MiB, so triple
    buffering only probes with the smaller tiles). Deliberately
    SHAPE-BLIND (`shape` ignored): _pick_block clamps preferences per
    dim at call time, so a clamped candidate is merely redundant in a
    sweep, never wrong — while shape-aware arithmetic at the 1024^3
    config of record would clamp everything feasible and stop pruning
    the combos that matter at larger N."""
    from tpukernels.tuning.roofline import sgemm_bytes_per_block

    blk = sgemm_bytes_per_block(params["bm"], params["bn"], params["bk"])
    depth = params.get("depth", 1)
    buf = 2 if depth == 1 else depth  # BlockSpec path double-buffers
    return buf * (blk["a"] + blk["b"]) + blk["c"] + blk["acc"]


# Declarative search space (docs/TUNING.md): sweep values carry the
# old tools/sgemm_tune.py grid rationale — bm 128/512 probes the
# A-reload vs accumulator-locality trade, bk 512 probes accumulator
# turnarounds at looser VMEM pressure, bn 1024 halves B residency to
# make room for the bk/bm probes; defaults-first ordering makes the
# control row the sweep's first candidate and --quick's base.
#
# Widened beyond block sizes (ISSUE 6): `depth` selects the pipeline —
# 1 = the BlockSpec-auto-pipelined path of record (measured 60.8
# TFLOPS), 2/3 = the manual ping-pong VMEM-slab + DMA-overlap variant
# (_sgemm_pipelined_call) the autotuner can now search; `order` picks
# the grid iteration order — "ij" streams B blocks per i-row (wide-bn
# default), "ji" streams A blocks per j-column (the reload trade
# flips when m >> n). Both ride the AOT cache key via the tunable env
# fingerprint, so each variant compiles and caches as its own program.
TUNABLES = SearchSpace(
    kernel="sgemm",
    metric="sgemm_gflops",
    bench_shape=(1024, 1024, 1024),
    bench_dtype="float32",
    sources=("tpukernels/kernels/sgemm.py",),
    tunables=(
        Tunable("bm", env="TPK_SGEMM_BM", default=256,
                values=(256, 128, 512)),
        Tunable("bn", env="TPK_SGEMM_BN", default=2048,
                values=(2048, 1024)),
        Tunable("bk", env="TPK_SGEMM_BK", default=1024,
                values=(1024, 512, 2048)),
        Tunable("depth", env="TPK_SGEMM_DEPTH", default=1,
                values=(1, 2, 3)),
        Tunable("order", env="TPK_SGEMM_ORDER", default="ij",
                values=("ij", "ji"), choice=True),
    ),
    vmem_budget_bytes=32 * 1024 * 1024,
    vmem_bytes=_vmem_bytes,
)


def _pick_block(dim: int, preferred: int, align: int) -> int:
    """Aligned block size <= preferred balancing padding vs tile size.

    Among aligned candidates whose padded total is within ~9% of the
    achievable minimum, picks the one giving the FEWEST blocks, then
    the least padding on ties. The two failure modes this splits:
    strict padding-minimization collapses awkward dims to degenerate
    tiles (k=2176 -> bk=128: 17 K-steps of accumulator turnaround;
    m=1042 -> bm=8: 6% systolic-row utilization), while a blind
    preferred-size block can nearly double the work (n=2176 with
    bn=2048 pads to 4096). A few percent padding buys full-size
    tiles; ties cost nothing."""
    if dim <= align:
        return dim
    # clamp below by one aligned block: a preference under `align`
    # (e.g. TPK_SGEMM_BN=1 via the tuner knobs) must degrade to the
    # smallest legal tile, not an empty candidate range
    hi = max(align, min(preferred, cdiv(dim, align) * align))
    cands = range(align, hi + 1, align)
    padded = lambda b: cdiv(dim, b) * b  # noqa: E731
    pad_min = min(padded(b) for b in cands)
    ok = [b for b in cands if padded(b) <= pad_min * 1.09]
    # fewest blocks first (big tiles), then least padding: padding
    # only buys something when it reduces the block count — at equal
    # count a bigger block is the same traffic for more zeros
    nb_min = min(cdiv(dim, b) for b in ok)
    return min(
        (b for b in ok if cdiv(dim, b) == nb_min), key=padded
    )


def _split_bf16(x):
    """x ≈ hi + lo with both parts bf16; hi carries the top 8 mantissa
    bits, lo the next 8.

    hi is computed with lax.reduce_precision(8, 7) — numerically the
    same round-to-nearest-even as astype(bfloat16), but NOT a convert
    pair: under jit, XLA-TPU's excess-precision pass folds
    f32→bf16→f32 converts to identity, which silently zeroes lo and
    degrades the whole split to single-pass bf16 (observed: rel error
    1e-2 instead of 1e-5)."""
    hi_f32 = jax.lax.reduce_precision(x, 8, 7)
    lo = (x - hi_f32).astype(jnp.bfloat16)
    return hi_f32.astype(jnp.bfloat16), lo


def _sgemm_kernel(mode, alpha_ref, beta_ref, *refs):
    """K-accumulating matmul kernel; one scaffolding, two operand modes.

    mode 'split3': refs = (ah, al, bh, bl, c, o, acc) — bf16_3x with
    the hi/lo split hoisted OUT of the kernel. Neither XLA's
    Precision.HIGH nor Mosaic lowers HIGH inside Pallas, so the three
    MXU passes are emitted by hand: a@b ≈ hi@hi + hi@lo + lo@hi, f32
    accumulate (dropping lo@lo loses ~2^-16 rel, measured 1.5e-5 at
    K=1024). Splitting in-kernel serialized VPU work against the MXU
    dots every K-step (and re-split each A block once per j, each B
    block once per i); the wrapper pre-splits once in one fused XLA
    pass, and the bf16 halves read the same HBM bytes as the f32
    originals.

    other modes: refs = (a, b, c, o, acc), mode is the jnp.dot
    precision ('float32' = bf16_6x, 'default' = single-pass bf16).
    """
    k = pl.program_id(2)
    dot = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    if mode == "split3":
        ah, al, bh, bl, c_ref, o_ref, acc_ref = refs
        update = dot(ah[:], bh[:]) + dot(ah[:], bl[:]) + dot(al[:], bh[:])
    else:
        a_ref, b_ref, c_ref, o_ref, acc_ref = refs
        update = dot(a_ref[:], b_ref[:], precision=mode)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += update

    @pl.when(k == pl.num_programs(2) - 1)
    def _commit():
        o_ref[:] = alpha_ref[0, 0] * acc_ref[:] + beta_ref[0, 0] * c_ref[:]


def _sgemm_pipelined_kernel(
    mode, nk, bm, bn, bk, depth, order, alpha_ref, beta_ref, *refs
):
    """Manual ping-pong pipeline over the K stream (depth >= 2).

    The streamed A/B operands live in HBM (``pl.ANY``); each grid step
    owns one (i, j) output tile and walks its nk K-blocks through
    ``depth`` VMEM slab slots with explicit async copies — the DMA for
    block kk+depth-1 is in flight while block kk feeds the MXU, the
    slab/sem machinery the stencil blocked kernels already half-use,
    generalized to a ring. Slot-reuse safety: the start targeting slot
    (kk-1) % depth is issued only after iteration kk-1's accumulator
    STORE, so the overwrite is ordered behind the last read of that
    slot.

    refs layout (python-unrolled, all indices static):
      streamed HBM operands (ah, al, bh, bl) or (a, b)
      c_ref, o_ref                        (VMEM blocks via BlockSpec)
      one (depth, ...) VMEM slab per streamed operand
      acc scratch (bm, bn) f32
      one DMA((depth,)) semaphore array per streamed operand
    """
    n_ops = 4 if mode == "split3" else 2
    hbm = refs[:n_ops]
    c_ref, o_ref = refs[n_ops], refs[n_ops + 1]
    slabs = refs[n_ops + 2:n_ops + 2 + n_ops]
    acc_ref = refs[n_ops + 2 + n_ops]
    sems = refs[n_ops + 3 + n_ops:]
    if order == "ji":
        j, i = pl.program_id(0), pl.program_id(1)
    else:
        i, j = pl.program_id(0), pl.program_id(1)

    def dma(idx, kk):
        slot = kk % depth
        if idx < n_ops // 2:  # A-like: (m, k) operand
            src = hbm[idx].at[pl.ds(i * bm, bm), pl.ds(kk * bk, bk)]
        else:  # B-like: (k, n) operand
            src = hbm[idx].at[pl.ds(kk * bk, bk), pl.ds(j * bn, bn)]
        return pltpu.make_async_copy(
            src, slabs[idx].at[slot], sems[idx].at[slot]
        )

    dot = functools.partial(jnp.dot, preferred_element_type=jnp.float32)
    for kk in range(min(depth - 1, nk)):  # prologue: fill the ring
        for idx in range(n_ops):
            dma(idx, kk).start()
    for kk in range(nk):  # static unroll (nk is small at these tiles)
        nxt = kk + depth - 1
        if nxt < nk and nxt >= depth - 1:
            for idx in range(n_ops):
                dma(idx, nxt).start()
        for idx in range(n_ops):
            dma(idx, kk).wait()
        slot = kk % depth
        if mode == "split3":
            ah, al, bh, bl = (s[slot] for s in slabs)
            update = dot(ah, bh) + dot(ah, bl) + dot(al, bh)
        else:
            a, b = (s[slot] for s in slabs)
            update = dot(a, b, precision=mode)
        if kk == 0:
            acc_ref[:] = update
        else:
            acc_ref[:] += update
    o_ref[:] = alpha_ref[0, 0] * acc_ref[:] + beta_ref[0, 0] * c_ref[:]


def _sgemm_pipelined_call(
    alpha, beta, operands, c, bm, bn, bk, depth, order, mode, interpret
):
    """pallas_call wrapper for the manual K-pipeline: grid over (i, j)
    only (K walks inside the kernel), streamed operands in pl.ANY,
    C/out as ordinary VMEM blocks."""
    m = c.shape[0]
    n = c.shape[1]
    k = operands[0].shape[1]
    nk = cdiv(k, bk)
    gm, gn = cdiv(m, bm), cdiv(n, bn)
    if order == "ji":
        grid = (gn, gm)
        c_map = lambda j, i: (i, j)  # noqa: E731
    else:
        grid = (gm, gn)
        c_map = lambda i, j: (i, j)  # noqa: E731
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    c_spec = pl.BlockSpec((bm, bn), c_map, memory_space=pltpu.VMEM)
    n_ops = len(operands)
    slab_shapes = [
        pltpu.VMEM(
            (depth, bm, bk) if idx < n_ops // 2 else (depth, bk, bn),
            operands[idx].dtype,
        )
        for idx in range(n_ops)
    ]
    return pl.pallas_call(
        functools.partial(
            _sgemm_pipelined_kernel, mode, nk, bm, bn, bk, depth, order
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[smem, smem] + [any_spec] * n_ops + [c_spec],
        out_specs=c_spec,
        scratch_shapes=slab_shapes
        + [pltpu.VMEM((bm, bn), jnp.float32)]
        + [pltpu.SemaphoreType.DMA((depth,)) for _ in range(n_ops)],
        compiler_params=CompilerParams(
            # manual DMAs + ring-slot reuse assume sequential steps
            dimension_semantics=("arbitrary", "arbitrary"),
            # depth slabs of the A/B pairs + C/out/acc: the TUNABLES
            # vmem model prunes candidates past 32 MiB; 64 leaves
            # Mosaic headroom for spills without the unrolled-slab
            # compile blowup docs/PERF.md warns about
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=4 * (m * k + k * n + 2 * m * n),
            transcendentals=0,
        ),
        interpret=interpret,
    )(alpha, beta, *operands, c)


@functools.partial(
    jax.jit,
    static_argnames=("bm", "bn", "bk", "depth", "order", "precision",
                     "interpret"),
)
def _sgemm_padded(
    alpha, beta, a, b, c, bm, bn, bk, depth=1, order="ij",
    precision="high", interpret=False,
):
    m, k = a.shape
    _, n = b.shape
    if precision == "high":
        a_hi, a_lo = _split_bf16(a)
        b_hi, b_lo = _split_bf16(b)
        operands, mode = (a_hi, a_lo, b_hi, b_lo), "split3"
    else:
        operands, mode = (a, b), precision
    if depth > 1:
        return _sgemm_pipelined_call(
            alpha, beta, operands, c, bm, bn, bk, depth, order, mode,
            interpret,
        )
    # depth 1: the BlockSpec-auto-pipelined path of record. `order`
    # permutes the two parallel grid dims (and with them which operand
    # re-streams): "ij" walks j fastest per i-row, "ji" the transpose.
    if order == "ji":
        grid = (cdiv(n, bn), cdiv(m, bm), cdiv(k, bk))
        a_map = lambda j, i, kk: (i, kk)  # noqa: E731
        b_map = lambda j, i, kk: (kk, j)  # noqa: E731
        c_map = lambda j, i, kk: (i, j)  # noqa: E731
    else:
        grid = (cdiv(m, bm), cdiv(n, bn), cdiv(k, bk))
        a_map = lambda i, j, kk: (i, kk)  # noqa: E731
        b_map = lambda i, j, kk: (kk, j)  # noqa: E731
        c_map = lambda i, j, kk: (i, j)  # noqa: E731
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    a_spec = pl.BlockSpec((bm, bk), a_map, memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((bk, bn), b_map, memory_space=pltpu.VMEM)
    c_spec = pl.BlockSpec((bm, bn), c_map, memory_space=pltpu.VMEM)
    common = dict(
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        out_specs=c_spec,
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            # The tall-K blocks need ~28 MiB once double-buffered at
            # the widest case (B hi+lo at 1024x2048 bf16 is 8 MiB
            # before buffering — 16 after), over Mosaic's 16 MiB
            # default scoped budget with only ~4 MiB headroom left
            # under 32. Don't enlarge any block without redoing this
            # arithmetic. 32 MiB stays safe compile-time-wise: flat
            # 2-D buffers, no unrolled-slab blowup (docs/PERF.md).
            vmem_limit_bytes=32 * 1024 * 1024,
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=4 * (m * k + k * n + 2 * m * n),
            transcendentals=0,
        ),
        interpret=interpret,
    )
    if mode == "split3":
        return pl.pallas_call(
            functools.partial(_sgemm_kernel, "split3"),
            in_specs=[smem, smem, a_spec, a_spec, b_spec, b_spec, c_spec],
            **common,
        )(alpha, beta, *operands, c)
    return pl.pallas_call(
        functools.partial(_sgemm_kernel, mode),
        in_specs=[smem, smem, a_spec, b_spec, c_spec],
        **common,
    )(alpha, beta, *operands, c)


def sgemm(
    alpha,
    a,
    b,
    beta,
    c,
    precision: str | None = None,
    interpret: bool | None = None,
):
    """alpha*A@B + beta*C for float32 matrices; pads to tile multiples.

    precision: 'high' (bf16_3x, default), 'float32' (bf16_6x, bitwise
    fp32), or 'default' (single-pass bf16); overridable via the
    TPK_SGEMM_PRECISION env var.
    """
    if interpret is None:
        interpret = default_interpret()
    if precision is None:
        precision = os.environ.get("TPK_SGEMM_PRECISION", "high")
    if precision not in ("high", "float32", "default"):
        raise ValueError(
            f"precision={precision!r}: expected 'high' (bf16_3x), "
            "'float32' (bf16_6x), or 'default' (single-pass bf16)"
        )
    m, k = a.shape
    k2, n = b.shape
    assert k == k2 and c.shape == (m, n)
    # Tall-K tiling: with the full K in one dot per grid step the
    # kernel sits at the bf16_3x compute ceiling (single-pass bf16
    # measures 184 TFLOPS; /3 = 61; measured 62 at 1024^3 vs 48 for
    # the 512^3 tiling this replaced). Wide bn amortizes A-block
    # reloads — bn prefers the full N up to 2048 (at 2048^3: 60.7
    # TFLOPS vs 52.7 with bn=1024); past 2048, B's double-buffered
    # hi+lo pair would blow the 32 MiB VMEM budget. Small bm keeps
    # A+C+acc in the remaining headroom.
    #
    # Tile PREFERENCES and pipeline knobs resolve through the tuning
    # subsystem (env TPK_SGEMM_{BM,BN,BK,DEPTH,ORDER} > tuned cache
    # entry for this shape/dtype/device > the TUNABLES defaults
    # above); alignment and padding safety stay with _pick_block
    # either way.
    prefs = resolve(TUNABLES, shape=(m, k, n), dtype=a.dtype.name)
    bm = _pick_block(m, prefs["bm"], 8)
    bn = _pick_block(n, prefs["bn"], 128)
    bk = _pick_block(k, prefs["bk"], 128)
    depth = max(1, prefs["depth"])
    order = prefs["order"]
    pm, pn, pk = (cdiv(m, bm) * bm, cdiv(n, bn) * bn, cdiv(k, bk) * bk)
    if (pm, pk) != (m, k):
        a = jnp.pad(a, ((0, pm - m), (0, pk - k)))
    if (pk, pn) != (k, n):
        b = jnp.pad(b, ((0, pk - k), (0, pn - n)))
    if (pm, pn) != (m, n):
        c = jnp.pad(c, ((0, pm - m), (0, pn - n)))
    alpha2 = jnp.asarray(alpha, jnp.float32).reshape(1, 1)
    beta2 = jnp.asarray(beta, jnp.float32).reshape(1, 1)
    out = _sgemm_padded(
        alpha2, beta2, a, b, c, bm, bn, bk,
        depth=depth, order=order,
        precision=precision, interpret=interpret,
    )
    return out[:m, :n]


def sgemm_reference(alpha, a, b, beta, c):
    """jnp oracle (mirrors the serial-C ijk golden variant).

    precision is pinned so the oracle stays fp32-accurate even when it
    happens to run on a TPU backend (default matmul precision is bf16
    there, which would corrupt the golden).
    """
    return alpha * jnp.dot(a, b, precision="float32") + beta * c
