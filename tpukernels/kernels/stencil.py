"""Jacobi stencils: 2D 5-point and 3D 7-point (SURVEY.md C6).

Reference config: 2D 4096^2, 1000 iters (BASELINE.json configs[2]);
metric Mcells/sec = X*Y(*Z)*iters / t. Update rule (fixed by the
serial-C oracle in c/stencil.c): interior cells become the mean of
their face neighbors (0.25 in 2D, 1/6 in 3D); boundary cells are held
fixed (Dirichlet).

TPU design — two Pallas paths chosen by problem size:

* small: whole grid fits in VMEM; neighbor shifts are concatenations
  (VPU) and one pallas_call performs one sweep.
* blocked: the grid lives in HBM (`pl.ANY`). The wrapper pads the
  blocked dimension by a ghost band on each side (8 rows in 2D so DMA
  row counts stay sublane-aligned; k planes in 3D), every kernel
  instance DMAs a ghost-extended slab into VMEM scratch, and all
  in-kernel slices are static (Mosaic requires sublane offsets
  provably 8-aligned; dynamic clamped offsets are not).

The blocked path is *temporally blocked*: k sweeps run back-to-back
on the VMEM slab per HBM pass (default k=8, env
TPK_STENCIL_K), cutting HBM traffic per sweep to 8/k bytes/cell and
lifting the single-chip roofline by k. Rows near a slab edge go stale
one-per-sweep (no true neighbors); the ghost band bounds that, so the
owned rows stay exact — measured ~2.9x at 4096^2 (56 -> 160 Gcells/s,
VPU-bound at k=8).

Ghost cells replicate the boundary cell and the boundary is Dirichlet
(held fixed), so ghosts stay consistent across iterations by
construction. The interior mask is always computed against the TRUE
dims, so padding (ghost rows, lane-alignment columns) never leaks into
the interior.

Iteration runs under `jax.lax.fori_loop` inside one jit, so XLA
double-buffers the ping-pong arrays and no host round-trips happen
between sweeps. Multi-chip variant (row-sharded, ppermute halos) lives
in tpukernels/parallel/collectives.py.
"""

from __future__ import annotations

import functools
import os
import sys

import jax
import jax.numpy as jnp

# drift-prone Pallas names resolve through the compat choke point
# (tpukernels/compat.py): this env may ship pltpu.TPUCompilerParams
# (jax 0.4.x) where the code was written against CompilerParams
from tpukernels.compat import CompilerParams, pl, pltpu
from tpukernels.tuning import SearchSpace, Tunable, resolve
from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

# Declarative search spaces (docs/TUNING.md): the temporal-blocking
# depth k (sweeps fused per HBM pass) is the 2D knob worth sweeping —
# docs/PERF.md records k>8 as VPU-bound (parked, docs/NEXT.md item 4),
# so the sweep stays within the ghost-band bound; the hand-rolled 2D
# ping-pong was built and REJECTED by measurement (107 vs 130
# Gcells/s, docs/PERF.md), so 2D gets no pipeline knob. 3D adds
# `depth` (ISSUE 6): 1 = today's copy-wait-compute slab, 2/3 = the
# ring-buffered slab prefetch (_jacobi3d_blocked_kernel) overlapping
# block zi+1's DMA with block zi's sweeps — the z-axis geometry has
# no out_specs pipelining to lose, unlike the rejected 2D rewrite.
# Slab geometry (bm/bz) self-adapts to the VMEM budget in the pickers
# below and is deliberately NOT a tunable: an env-forced slab that
# ignores the budget arithmetic would fail remote compile, not run
# slower. No vmem model for the same reason — every candidate is
# feasible by construction (_pick_bz divides the budget by depth).
TUNABLES = (
    SearchSpace(
        kernel="stencil2d",
        metric="stencil2d_mcells_s",
        bench_shape=(4096, 4096),
        bench_dtype="float32",
        sources=("tpukernels/kernels/stencil.py",),
        tunables=(
            Tunable("k", env="TPK_STENCIL_K", default=8,
                    values=(8, 6, 4, 2)),
        ),
    ),
    SearchSpace(
        kernel="stencil3d",
        metric="stencil3d_mcells_s",
        bench_shape=(384, 384, 384),
        bench_dtype="float32",
        sources=("tpukernels/kernels/stencil.py",),
        tunables=(
            Tunable("k", env="TPK_STENCIL_K", default=8,
                    values=(8, 6, 4, 2)),
            Tunable("depth", env="TPK_STENCIL_DEPTH", default=1,
                    values=(1, 2, 3)),
        ),
    ),
)

_SMALL_BYTES = 4 * 1024 * 1024  # whole-grid-in-VMEM threshold
_VMEM_BUDGET = 10 * 1024 * 1024  # slab + (pipelined) out blocks must fit
# temporal blocking materializes a few full-slab temporaries per fused
# sweep; the default 16 MiB Mosaic scoped-vmem limit is too tight
_COMPILER_PARAMS = CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)


def _pick_bm(wp: int) -> int:
    """Rows per 2D block: slab (bm+16, wp) + up to two out blocks
    (bm, wp) must fit the VMEM budget; multiple of 8."""
    total_rows = _VMEM_BUDGET // (4 * wp)
    bm = (total_rows - 2 * _GHOST2D) // 3
    return max(8, min(512, bm // 8 * 8))


def _pick_bz(hp: int, wp: int, k: int = 1, depth: int = 1) -> int:
    """z-planes per 3D block: ``depth`` slabs of (bz+2k) planes + two
    out blocks of bz planes inside a 32 MiB budget — at depth 1
    exactly the old (total - 2k) // 3. Thin slabs lose most of their
    planes to ghost recompute (at 16 MiB / 384² the ghost fraction
    was 57% and measured 65 Gcells/s vs 83.6 at 32 MiB); 40+ MiB fails
    remote compile with VMEM exhaustion, and very large unrolled
    slabs (tried up to ~96 MiB) sent Mosaic compile times through
    the roof."""
    total_planes = (32 * 1024 * 1024) // (4 * hp * wp)
    bz = (total_planes - 2 * k * depth) // (2 + depth)
    return max(1, min(32, bz))


def _shift_cols(x, left: bool):
    """Neighbor values along the lane dim: col j gets col j-1
    (left=True) or j+1. Edge cols replicate; they are boundary cells
    and get masked anyway."""
    if left:
        return jnp.concatenate([x[:, :1], x[:, :-1]], axis=1)
    return jnp.concatenate([x[:, 1:], x[:, -1:]], axis=1)


# ---------------------------------------------------------------- 2D

def _mask2d(row0, bm, w_blk, h, w, row_offset):
    """Interior mask for a (bm, w_blk) block whose first row is global
    padded row `row0`; real row = padded row - row_offset."""
    gr = row0 - row_offset + jax.lax.broadcasted_iota(
        jnp.int32, (bm, w_blk), 0
    )
    gc = jax.lax.broadcasted_iota(jnp.int32, (bm, w_blk), 1)
    return (gr > 0) & (gr < h - 1) & (gc > 0) & (gc < w - 1)


def _jacobi2d_small_kernel(h, w, x_ref, o_ref):
    x = x_ref[:]
    hp, wp = x.shape
    north = jnp.concatenate([x[:1], x[:-1]], axis=0)
    south = jnp.concatenate([x[1:], x[-1:]], axis=0)
    out = 0.25 * (north + south + _shift_cols(x, True) + _shift_cols(x, False))
    o_ref[:] = jnp.where(_mask2d(0, hp, wp, h, w, 0), out, x)


_GHOST2D = 8  # ghost rows each side; 8 so DMA row-counts stay 8-aligned


def _jacobi2d_blocked_kernel(h, w, bm, k, x_hbm, o_ref, slab, sem):
    # x_hbm has 8 ghost rows above and below (padded height =
    # Hp + 16). Block i owns padded rows [8 + i*bm, 8 + (i+1)*bm) and
    # DMAs the slab [i*bm, i*bm + bm + 16): the start offset is
    # bm-aligned and the row count (bm+16) is a sublane multiple —
    # both Mosaic requirements.
    #
    # Temporal blocking: k <= _GHOST2D sweeps run on the VMEM slab per
    # HBM pass, dividing HBM traffic per sweep by k. Rows near the
    # slab edge lack true neighbors, so each sweep invalidates one
    # more row inward from each end; with ghost depth 8 the owned rows
    # [g, g+bm) are still exact after k <= 8 sweeps. Global-boundary
    # ghost rows replicate Dirichlet cells the interior mask holds
    # fixed, so they stay exact across all k sweeps by construction.
    i = pl.program_id(0)
    g = _GHOST2D
    rows = bm + 2 * g
    wp = slab.shape[1]
    copy = pltpu.make_async_copy(x_hbm.at[pl.ds(i * bm, rows), :], slab, sem)
    copy.start()
    copy.wait()
    # the global-interior mask is sweep-invariant: compute once
    mask = _mask2d(i * bm, rows, wp, h, w, g)
    cur = slab[:]
    for _ in range(k):  # static unroll
        north = jnp.concatenate([cur[:1], cur[:-1]], axis=0)
        south = jnp.concatenate([cur[1:], cur[-1:]], axis=0)
        out = 0.25 * (
            north + south + _shift_cols(cur, True) + _shift_cols(cur, False)
        )
        cur = jnp.where(mask, out, cur)
    o_ref[:] = cur[g : g + bm, :]


def _sweep2d_small(x, h, w, interpret):
    hp, wp = x.shape
    return pl.pallas_call(
        functools.partial(_jacobi2d_small_kernel, h, w),
        out_shape=jax.ShapeDtypeStruct((hp, wp), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x)


def _sweep2d_blocked(x, h, w, bm, k, interpret):
    # x: (Hp + 16, wp) with 8 ghost rows at each end; Hp % bm == 0.
    # Runs k fused Jacobi sweeps per HBM pass (see kernel docstring).
    hp2, wp = x.shape
    g = _GHOST2D
    nblk = (hp2 - 2 * g) // bm
    out = pl.pallas_call(
        functools.partial(_jacobi2d_blocked_kernel, h, w, bm, k),
        out_shape=jax.ShapeDtypeStruct((hp2 - 2 * g, wp), x.dtype),
        grid=(nblk,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (bm, wp), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((bm + 2 * g, wp), x.dtype),
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(x)
    # re-attach ghost rows (held fixed) for the next pass
    return jnp.concatenate([x[:g], out, x[-g:]], axis=0)


@functools.partial(
    jax.jit, static_argnames=("h", "w", "iters", "bm", "k", "interpret")
)
def _jacobi2d_jit(x, h, w, iters, bm, k, interpret):
    if bm:
        passes, rem = divmod(iters, k)
        x = jax.lax.fori_loop(
            0,
            passes,
            lambda _, v: _sweep2d_blocked(v, h, w, bm, k, interpret),
            x,
        )
        if rem:
            x = _sweep2d_blocked(x, h, w, bm, rem, interpret)
        return x
    sweep = lambda v: _sweep2d_small(v, h, w, interpret)  # noqa: E731
    return jax.lax.fori_loop(0, iters, lambda _, v: sweep(v), x)


def jacobi2d(
    x, iters: int, interpret: bool | None = None, k: int | None = None
):
    """Run `iters` Jacobi 5-point sweeps on (H, W) float32.

    `k` is the temporal-blocking depth (sweeps fused per HBM pass) for
    the blocked path, 1..8; default 8, resolved via the tuning
    subsystem (env TPK_STENCIL_K > tuned cache > default)."""
    if interpret is None:
        interpret = default_interpret()
    h, w = x.shape
    if k is None:
        k = resolve(TUNABLES[0], shape=(h, w), dtype=x.dtype.name)["k"]
    k = max(1, min(k, _GHOST2D))
    wp = max(cdiv(w, LANES) * LANES, LANES)
    bm = _pick_bm(wp)
    # blocked purely by size: the small path holds the whole grid in
    # VMEM under Mosaic's default scoped limit, so any >4 MiB grid
    # must take the blocked path (h < bm is handled by padding rows
    # up to one block)
    blocked = h * wp * 4 > _SMALL_BYTES
    pads = [(0, 0), (0, wp - w)]
    if blocked:
        # 8 ghost rows each side + round rows up to a block multiple
        g = _GHOST2D
        pads[0] = (g, g + cdiv(h, bm) * bm - h)
    x = jnp.pad(x, pads, mode="edge") if pads != [(0, 0), (0, 0)] else x
    out = _jacobi2d_jit(
        x, h, w, int(iters), bm if blocked else 0, k, interpret
    )
    if blocked:
        out = out[_GHOST2D : _GHOST2D + h]
    return out[:, :w]


def jacobi2d_reference(x, iters: int):
    """jnp oracle mirroring the serial-C golden variant."""

    def sweep(_, v):
        out = 0.25 * (
            jnp.roll(v, 1, 0) + jnp.roll(v, -1, 0)
            + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1)
        )
        h, w = v.shape
        gr = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0)
        gc = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1)
        interior = (gr > 0) & (gr < h - 1) & (gc > 0) & (gc < w - 1)
        return jnp.where(interior, out, v)

    return jax.lax.fori_loop(0, iters, sweep, x)


# ---------------------------------------------------------------- 3D

def _mask3d(z0, bz, h_blk, w_blk, d, h, w, z_offset):
    gz = z0 - z_offset + jax.lax.broadcasted_iota(
        jnp.int32, (bz, h_blk, w_blk), 0
    )
    gy = jax.lax.broadcasted_iota(jnp.int32, (bz, h_blk, w_blk), 1)
    gx = jax.lax.broadcasted_iota(jnp.int32, (bz, h_blk, w_blk), 2)
    return (
        (gz > 0) & (gz < d - 1)
        & (gy > 0) & (gy < h - 1)
        & (gx > 0) & (gx < w - 1)
    )


def _stencil3d_sum(center, zm, zp):
    ym = jnp.concatenate([center[:, :1], center[:, :-1]], axis=1)
    yp = jnp.concatenate([center[:, 1:], center[:, -1:]], axis=1)
    xm = jnp.concatenate([center[:, :, :1], center[:, :, :-1]], axis=2)
    xp = jnp.concatenate([center[:, :, 1:], center[:, :, -1:]], axis=2)
    return (zm + zp + ym + yp + xm + xp) * (1.0 / 6.0)


def _jacobi3d_small_kernel(d, h, w, x_ref, o_ref):
    x = x_ref[:]
    dp, hp, wp = x.shape
    zm = jnp.concatenate([x[:1], x[:-1]], axis=0)
    zp = jnp.concatenate([x[1:], x[-1:]], axis=0)
    out = _stencil3d_sum(x, zm, zp)
    o_ref[:] = jnp.where(_mask3d(0, dp, hp, wp, d, h, w, 0), out, x)


def _jacobi3d_blocked_kernel(
    d, h, w, bz, g, k, depth, x_hbm, o_ref, slab, sem
):
    # Temporal blocking in z: the HBM array carries a FIXED ghost depth
    # g (set by the wrapper's padding) while k <= g sweeps run per pass
    # — the remainder pass (k = iters % g) reuses the same geometry
    # with fewer sweeps, so ghost depth must not be derived from the
    # sweep count. Same containment argument as the 2D kernel: the h/w
    # extents are fully in-slab, so only z edges go stale, one plane
    # inward per sweep, bounded by g.
    #
    # Pipelining (depth >= 2, TPK_STENCIL_DEPTH): the slab is a ring
    # of `depth` slots persisting across the sequential grid — step 0
    # fills slots for blocks 0..depth-2, every step starts block
    # zi+depth-1's DMA before waiting on its own, so the next slab
    # streams in while this one sweeps. Slot-reuse safety: the start
    # issued at step zi targets slot (zi-1) % depth, whose last reader
    # (step zi-1) already committed its o_ref store — grid steps are
    # sequential on TPU. depth == 1 degenerates to start-then-wait in
    # the same step, byte-identical to the unpipelined original.
    zi = pl.program_id(0)
    nblk = pl.num_programs(0)
    planes = bz + 2 * g
    hp, wp = slab.shape[2], slab.shape[3]

    def dma(b, slot):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(b * bz, planes)], slab.at[slot], sem.at[slot]
        )

    if depth > 1:
        @pl.when(zi == 0)
        def _prologue():
            for b in range(min(depth - 1, nblk)):
                dma(b, b % depth).start()
    nxt = zi + depth - 1

    @pl.when(nxt < nblk)
    def _prefetch():
        dma(nxt, nxt % depth).start()

    slot = zi % depth
    dma(zi, slot).wait()
    mask = _mask3d(zi * bz, planes, hp, wp, d, h, w, g)
    cur = slab[slot]
    for _ in range(k):  # static unroll
        zm = jnp.concatenate([cur[:1], cur[:-1]], axis=0)
        zp = jnp.concatenate([cur[1:], cur[-1:]], axis=0)
        out = _stencil3d_sum(cur, zm, zp)
        cur = jnp.where(mask, out, cur)
    o_ref[:] = cur[g : g + bz]


def _sweep3d_small(x, d, h, w, interpret):
    dp, hp, wp = x.shape
    return pl.pallas_call(
        functools.partial(_jacobi3d_small_kernel, d, h, w),
        out_shape=jax.ShapeDtypeStruct((dp, hp, wp), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x)


def _sweep3d_blocked(x, d, h, w, bz, g, k, depth, interpret):
    # x: (Dp + 2g, hp, wp) with g ghost planes at each end; runs k <= g
    # fused sweeps per HBM pass through a `depth`-slot slab ring
    dp2, hp, wp = x.shape
    nblk = (dp2 - 2 * g) // bz
    out = pl.pallas_call(
        functools.partial(
            _jacobi3d_blocked_kernel, d, h, w, bz, g, k, depth
        ),
        out_shape=jax.ShapeDtypeStruct((dp2 - 2 * g, hp, wp), x.dtype),
        grid=(nblk,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (bz, hp, wp), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[
            pltpu.VMEM((depth, bz + 2 * g, hp, wp), x.dtype),
            pltpu.SemaphoreType.DMA((depth,)),
        ],
        compiler_params=_COMPILER_PARAMS,
        interpret=interpret,
    )(x)
    return jnp.concatenate([x[:g], out, x[-g:]], axis=0)


@functools.partial(
    jax.jit,
    static_argnames=("d", "h", "w", "iters", "bz", "k", "depth",
                     "interpret"),
)
def _jacobi3d_jit(x, d, h, w, iters, bz, k, depth, interpret):
    if bz:
        passes, rem = divmod(iters, k)
        x = jax.lax.fori_loop(
            0,
            passes,
            lambda _, v: _sweep3d_blocked(
                v, d, h, w, bz, k, k, depth, interpret
            ),
            x,
        )
        if rem:
            x = _sweep3d_blocked(x, d, h, w, bz, k, rem, depth, interpret)
        return x
    sweep = lambda v: _sweep3d_small(v, d, h, w, interpret)  # noqa: E731
    return jax.lax.fori_loop(0, iters, lambda _, v: sweep(v), x)


def jacobi3d(
    x,
    iters: int,
    interpret: bool | None = None,
    k: int | None = None,
    depth: int | None = None,
):
    """Run `iters` Jacobi 7-point sweeps on (D, H, W) float32.

    `k` is the temporal-blocking depth (sweeps fused per HBM pass) for
    the blocked path; default 8, resolved via the tuning subsystem
    (env TPK_STENCIL_K > tuned cache > default). `depth` is the slab
    pipeline depth — 1 (default) is the copy-wait-compute path of
    record, 2/3 ring-buffer the slab so the next block's DMA overlaps
    this block's sweeps (TPK_STENCIL_DEPTH; _pick_bz shrinks bz to
    keep depth slabs inside the same VMEM budget)."""
    if interpret is None:
        interpret = default_interpret()
    d, h, w = x.shape
    params = resolve(TUNABLES[1], shape=(d, h, w), dtype=x.dtype.name)
    if k is None:
        k = params["k"]
    if depth is None:
        depth = params["depth"]
    k = max(1, min(k, 8))
    depth = max(1, int(depth))
    wp = max(cdiv(w, LANES) * LANES, LANES)
    hp8 = cdiv(h, 8) * 8
    # joint (k, bz) pick: wide planes shrink bz toward its floor of 1,
    # and a slab of (bz + 2k) planes with k >> bz both blows the
    # 100 MiB vmem limit (e.g. 7 MiB planes at k=8: 17 planes =
    # 120 MiB) and drowns in ghost recompute. Walk k down until the
    # budget supports bz >= k rather than clamping against a bz that
    # assumed the larger k (a 2 MiB plane at k=8 would collapse to
    # bz=1/k=1 when bz=4/k=2 fits).
    for kk in range(k, 0, -1):
        bz = _pick_bz(hp8, wp, kk, depth)
        if bz >= kk:  # always true by kk=1 (_pick_bz floors at 1)
            k = kk
            break
    # blocked purely by size: the small path holds the whole grid (and
    # its sweep temporaries) in VMEM under Mosaic's default scoped
    # limit, so any >4 MiB grid must take the blocked path — bz and
    # padding handle shallow d (bz <= d keeps pad waste < one block)
    bz = min(bz, d)
    blocked = d * h * wp * 4 > _SMALL_BYTES
    if (
        os.environ.get("TPK_STENCIL_LOG") == "1"
        or os.environ.get("TPK_BENCH_PREWARM") == "1"
    ):
        # wedge-postmortem breadcrumb (VERDICT r4 weak #3): the chosen
        # slab geometry, printed at trace time so it lands in the
        # bench child's stderr log BEFORE any remote compile/execute.
        # slab=none on the unblocked path (ADVICE r5): printing a slab
        # tuple the kernel never materializes would let a postmortem
        # misattribute an unblocked-path hang to slab geometry.
        if blocked:
            slab_mib = depth * (bz + 2 * k) * hp8 * wp * 4 / 2**20
            geom = (f"slab=({depth}x{bz + 2 * k},{hp8},{wp}) "
                    f"{slab_mib:.1f} MiB")
        else:
            geom = "slab=none"
        print(
            f"# jacobi3d: d={d} h={h} w={w} blocked={blocked} bz={bz} "
            f"k={k} depth={depth} {geom} "
            f"vmem_limit={_COMPILER_PARAMS.vmem_limit_bytes // 2**20} MiB",
            file=sys.stderr,
            flush=True,
        )
    pads = [(0, 0), (0, 0), (0, wp - w)]
    if blocked:
        pads[0] = (k, k + cdiv(d, bz) * bz - d)
        # sublane dim (h) must be an 8-multiple for the slab DMA
        pads[1] = (0, hp8 - h)
    x = (
        jnp.pad(x, pads, mode="edge")
        if pads != [(0, 0), (0, 0), (0, 0)]
        else x
    )
    out = _jacobi3d_jit(
        x, d, h, w, int(iters), bz if blocked else 0, k, depth, interpret
    )
    if blocked:
        out = out[k : k + d]
    return out[:, :h, :w]


def jacobi3d_reference(x, iters: int):
    def sweep(_, v):
        out = (
            jnp.roll(v, 1, 0) + jnp.roll(v, -1, 0)
            + jnp.roll(v, 1, 1) + jnp.roll(v, -1, 1)
            + jnp.roll(v, 1, 2) + jnp.roll(v, -1, 2)
        ) * (1.0 / 6.0)
        d, h, w = v.shape
        gz = jax.lax.broadcasted_iota(jnp.int32, (d, h, w), 0)
        gy = jax.lax.broadcasted_iota(jnp.int32, (d, h, w), 1)
        gx = jax.lax.broadcasted_iota(jnp.int32, (d, h, w), 2)
        interior = (
            (gz > 0) & (gz < d - 1)
            & (gy > 0) & (gy < h - 1)
            & (gx > 0) & (gx < w - 1)
        )
        return jnp.where(interior, out, v)

    return jax.lax.fori_loop(0, iters, sweep, x)
