"""O(N^2) direct N-body (SURVEY.md C8).

Reference config: 65 536 bodies, direct all-pairs gravity with
Plummer softening, leapfrog-style integration (BASELINE.json
configs[4]). Metric: interactions/sec = N^2 * steps / t.

TPU design: SoA float32 arrays shaped (1, N) so bodies live on the
lane dimension. The Pallas force kernel grids over i-blocks; each
grid step holds its (bi,) i-bodies as a column tile and sweeps all
j-bodies in (1, bj) lane chunks held in VMEM (the whole 65 536-body
j-set is only 1 MiB), accumulating (bi, bj) pairwise partial
accelerations on the VPU — the GPU-Gems shared-memory j-tiling
pattern, restated for VMEM (SURVEY.md C8). Self-interaction
contributes zero automatically (dr = 0), and padded bodies carry
mass 0 so they contribute nothing.

Integration (v += a dt; p += v dt) is plain fused VPU work; `steps`
sweeps run under one jit via fori_loop. The multi-chip variant
(i-shard + psum, or j-ring via ppermute) lives in
tpukernels/parallel/collectives.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpukernels.compat import pl, pltpu
from tpukernels.tuning import SearchSpace, Tunable, resolve
from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

_BI = 256  # i-bodies per grid step
_BJ = 2048  # j-bodies per inner chunk


def _vmem_bytes(params, shape=None):
    """Resident j-set (4 SoA f32 arrays over n bodies) + the (bi, bj)
    pairwise VPU temporaries (~6 live at once: dx/dy/dz/r2/inv_r/w) +
    the streamed (1, bi) i/out tiles (negligible). Shape-aware: the
    j-set term is what actually scales."""
    n = shape[0] if shape else 1 << 16
    n_pad = cdiv(n, LANES) * LANES
    return 4 * n_pad * 4 + 6 * params["bi"] * params["bj"] * 4


# Declarative search space (docs/TUNING.md). bi trades grid-step count
# against the (bi, bj) VPU tile's register/VMEM pressure; bj trades
# inner-loop trip count against the same. Defaults are the shipped
# GPU-Gems-style tiling the baseline was measured at.
TUNABLES = SearchSpace(
    kernel="nbody",
    metric="nbody_ginter_s",
    bench_shape=(1 << 16,),
    bench_dtype="float32",
    sources=("tpukernels/kernels/nbody.py",),
    tunables=(
        Tunable("bi", env="TPK_NBODY_BI", default=_BI,
                values=(256, 128, 512)),
        Tunable("bj", env="TPK_NBODY_BJ", default=_BJ,
                values=(2048, 1024, 4096)),
    ),
    vmem_budget_bytes=64 * 1024 * 1024,
    vmem_bytes=_vmem_bytes,
)


def _forces_kernel(n_pad, bi, bj, eps2_ref, xi_ref, yi_ref, zi_ref,
                   xj_ref, yj_ref, zj_ref, mj_ref,
                   ax_ref, ay_ref, az_ref):
    eps2 = eps2_ref[0, 0]
    # i-bodies as columns: (1, bi) -> (bi, 1)
    xi = xi_ref[:].reshape(bi, 1)
    yi = yi_ref[:].reshape(bi, 1)
    zi = zi_ref[:].reshape(bi, 1)

    nchunks = n_pad // bj

    def chunk(c, acc):
        ax, ay, az = acc
        j0 = c * bj
        xj = xj_ref[:, pl.ds(j0, bj)]
        yj = yj_ref[:, pl.ds(j0, bj)]
        zj = zj_ref[:, pl.ds(j0, bj)]
        mj = mj_ref[:, pl.ds(j0, bj)]
        dx = xj - xi  # (bi, bj)
        dy = yj - yi
        dz = zj - zi
        r2 = dx * dx + dy * dy + dz * dz + eps2
        inv_r = jax.lax.rsqrt(r2)
        w = mj * inv_r * inv_r * inv_r  # m_j / r^3
        ax = ax + jnp.sum(w * dx, axis=1, keepdims=True)
        ay = ay + jnp.sum(w * dy, axis=1, keepdims=True)
        az = az + jnp.sum(w * dz, axis=1, keepdims=True)
        return ax, ay, az

    zero = jnp.zeros((bi, 1), jnp.float32)
    ax, ay, az = jax.lax.fori_loop(0, nchunks, chunk, (zero, zero, zero))
    ax_ref[:] = ax.reshape(1, bi)
    ay_ref[:] = ay.reshape(1, bi)
    az_ref[:] = az.reshape(1, bi)


def _forces(px, py, pz, m, eps2, bi, bj, interpret):
    n_pad = px.shape[1]
    bi = min(bi, n_pad)
    bj = min(bj, n_pad)
    # the j-sweep advances in exact bj strides (pl.ds over the resident
    # arrays): a bj that doesn't divide n_pad would silently drop the
    # remainder bodies, so lane-align the preference and degrade to the
    # next 128-multiple that divides (terminates at 128 — n_pad is
    # always a LANES multiple)
    bj = max(LANES, bj // LANES * LANES)
    while n_pad % bj:
        bj -= LANES
    grid = (cdiv(n_pad, bi),)
    ispec = pl.BlockSpec((1, bi), lambda i: (0, i), memory_space=pltpu.VMEM)
    jspec = pl.BlockSpec(memory_space=pltpu.VMEM)  # whole array resident
    sspec = pl.BlockSpec(memory_space=pltpu.SMEM)
    out_shape = jax.ShapeDtypeStruct((1, n_pad), jnp.float32)
    return pl.pallas_call(
        functools.partial(_forces_kernel, n_pad, bi, bj),
        out_shape=(out_shape, out_shape, out_shape),
        grid=grid,
        in_specs=[sspec, ispec, ispec, ispec, jspec, jspec, jspec, jspec],
        out_specs=(ispec, ispec, ispec),
        cost_estimate=pl.CostEstimate(
            flops=20 * n_pad * bi,  # per grid step pairwise work
            bytes_accessed=4 * (7 * n_pad),
            transcendentals=n_pad * bi,
        ),
        interpret=interpret,
    )(eps2.reshape(1, 1), px, py, pz, px, py, pz, m)


@functools.partial(
    jax.jit, static_argnames=("steps", "bi", "bj", "interpret")
)
def _nbody_jit(px, py, pz, vx, vy, vz, m, dt, eps2, steps, bi, bj,
               interpret):
    def step(_, s):
        px, py, pz, vx, vy, vz = s
        ax, ay, az = _forces(px, py, pz, m, eps2, bi, bj, interpret)
        vx = vx + ax * dt
        vy = vy + ay * dt
        vz = vz + az * dt
        px = px + vx * dt
        py = py + vy * dt
        pz = pz + vz * dt
        return px, py, pz, vx, vy, vz

    return jax.lax.fori_loop(0, steps, step, (px, py, pz, vx, vy, vz))


def nbody_step(px, py, pz, vx, vy, vz, m, dt=1e-3, eps=1e-2, steps=1,
               interpret: bool | None = None):
    """Advance N bodies `steps` leapfrog steps. 1-D float32 SoA inputs;
    returns updated (px, py, pz, vx, vy, vz).

    Tile sizes resolve through the tuning subsystem (env
    TPK_NBODY_{BI,BJ} > tuned cache for this shape/dtype/device >
    shipped defaults 256/2048); _forces clamps them to the padded
    body count and bj to an exact stride."""
    if interpret is None:
        interpret = default_interpret()
    n = px.size
    tiles = resolve(TUNABLES, shape=(n,), dtype=px.dtype.name)
    pad = cdiv(n, LANES) * LANES - n
    arrs = [a.reshape(1, -1) for a in (px, py, pz, vx, vy, vz, m)]
    if pad:
        # padded bodies: mass 0 at the origin -> zero contribution
        arrs = [jnp.pad(a, ((0, 0), (0, pad))) for a in arrs]
    px2, py2, pz2, vx2, vy2, vz2, m2 = arrs
    out = _nbody_jit(
        px2, py2, pz2, vx2, vy2, vz2, m2,
        jnp.float32(dt), jnp.float32(eps * eps), int(steps),
        tiles["bi"], tiles["bj"], interpret
    )
    return tuple(a.reshape(-1)[:n] for a in out)


def nbody_reference(px, py, pz, vx, vy, vz, m, dt=1e-3, eps=1e-2, steps=1):
    """jnp oracle (mirrors the serial-C double loop)."""
    eps2 = jnp.float32(eps * eps)
    dt = jnp.float32(dt)

    def step(_, s):
        px, py, pz, vx, vy, vz = s
        dx = px[None, :] - px[:, None]
        dy = py[None, :] - py[:, None]
        dz = pz[None, :] - pz[:, None]
        r2 = dx * dx + dy * dy + dz * dz + eps2
        w = m[None, :] * jax.lax.rsqrt(r2) ** 3
        ax = jnp.sum(w * dx, axis=1)
        ay = jnp.sum(w * dy, axis=1)
        az = jnp.sum(w * dz, axis=1)
        vx = vx + ax * dt
        vy = vy + ay * dt
        vz = vz + az * dt
        return px + vx * dt, py + vy * dt, pz + vz * dt, vx, vy, vz

    return jax.lax.fori_loop(0, steps, step, (px, py, pz, vx, vy, vz))
