"""Headline benchmark: prints ONE JSON line for the driver.

Metric of record (BASELINE.md): SGEMM GFLOPS/chip at 1024^3 fp32 on
the attached TPU. Secondary metrics (stencil Mcells/s, nbody
Ginter/s, scan/histogram Melem/s) ride along in "details".

Timing discipline (see .claude/skills/verify/SKILL.md): the axon
tunnel makes device-side block_until_ready unreliable and early-
process readings ~100x off, so every measurement warms >= 3 calls and
forces completion by materializing a 4-byte scalar reduction.
"""

from __future__ import annotations

import json
import signal
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

_BENCH_TIMEOUT_S = 600  # per-benchmark watchdog (tunnel can wedge)


class _Timeout(Exception):
    pass


def _with_timeout(fn, seconds=_BENCH_TIMEOUT_S):
    """Run fn() under SIGALRM so a wedged TPU tunnel skips one metric
    instead of hanging the whole round."""

    def handler(signum, frame):
        raise _Timeout(f"exceeded {seconds}s")

    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(seconds)
    try:
        return fn()
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _timeit(fn, *args, reps=10, warmup=3):
    """Seconds/call; fn must return something tiny (scalar)."""
    for _ in range(warmup):
        np.asarray(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(fn(*args))
        t1 = time.perf_counter()
        best = min(best, t1 - t0)
    return best


def bench_sgemm(m=1024):
    from tpukernels.kernels.sgemm import sgemm

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
    c = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
    f = jax.jit(lambda a, b, c: jnp.sum(sgemm(1.5, a, b, 0.5, c)))
    t = _timeit(f, a, b, c, reps=20)
    return 2.0 * m**3 / t / 1e9


def bench_stencil(n=4096, iters=100):
    from tpukernels.kernels.stencil import jacobi2d

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((n, n)), jnp.float32)
    f = jax.jit(lambda x: jnp.sum(jacobi2d(x, iters)))
    t = _timeit(f, x, reps=5)
    return float(n) * n * iters / t / 1e6


def bench_nbody(n=65536, steps=2):
    from tpukernels.kernels.nbody import nbody_step

    rng = np.random.default_rng(2)
    args = tuple(
        jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(6)
    ) + (jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),)
    f = jax.jit(lambda *a: jnp.sum(nbody_step(*a, steps=steps)[0]))
    t = _timeit(f, *args, reps=5)
    return float(n) * n * steps / t / 1e9


def bench_scan_hist(n=1 << 22):
    from tpukernels.kernels.histogram import histogram
    from tpukernels.kernels.scan import inclusive_scan

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 256, n), jnp.int32)
    f = jax.jit(
        lambda x: inclusive_scan(x)[:1] + histogram(x, 256)[:1]
    )
    t = _timeit(f, x, reps=5)
    return float(n) / t / 1e6


def main():
    results = {}
    for name, fn in [
        ("sgemm_gflops", bench_sgemm),
        ("stencil2d_mcells_s", bench_stencil),
        ("nbody_ginter_s", bench_nbody),
        ("scan_hist_melem_s", bench_scan_hist),
    ]:
        try:
            results[name] = round(_with_timeout(fn), 2)
            print(f"# {name}: {results[name]}", file=sys.stderr)
            sys.stderr.flush()
        except Exception as e:  # keep the headline alive if one fails
            results[name] = None
            print(f"# {name} FAILED: {e}", file=sys.stderr)
            sys.stderr.flush()

    headline = results.get("sgemm_gflops")
    try:
        with open(
            __file__.replace("bench.py", "BASELINE.json"), "r"
        ) as f:
            published = json.load(f).get("published", {})
    except Exception:
        published = {}
    base = published.get("sgemm_gflops")
    vs = round(headline / base, 3) if (headline and base) else 1.0

    print(
        json.dumps(
            {
                "metric": "sgemm_gflops_per_chip",
                "value": headline,
                "unit": "GFLOPS",
                "vs_baseline": vs,
                "details": results,
            }
        )
    )


if __name__ == "__main__":
    main()
