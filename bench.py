"""Headline benchmark: prints ONE JSON line for the driver.

Metric of record (BASELINE.md): SGEMM GFLOPS/chip at 1024^3 fp32 on
the attached TPU. Secondary metrics (stencil Mcells/s, nbody
Ginter/s, scan/histogram Melem/s, saxpy GB/s) ride along in "details".

Timing discipline: the axon PJRT tunnel carries a fixed ~65 ms
host<->device round-trip per dispatched program, which would swamp any
sub-ms kernel (a 1024^3 matmul is ~80 us of MXU time). So every metric
is measured as a *slope*: the kernel's iteration loop runs on-device
(lax.fori_loop / the kernel's own `iters`/`steps` argument) at two
repeat counts R_small and R_big, and the per-iteration time is
(t_big - t_small) / (R_big - R_small). The fixed round-trip and any
other per-call constant cancels exactly; compile time is excluded by
warm-up calls as usual. Each loop body carries a data dependence on
the previous iteration so XLA cannot hoist or batch the work.

Tuning integration (docs/TUNING.md): each metric's kernel resolves its
block geometry per call via tpukernels/tuning with precedence
env-override > tuned-cache > shipped-default, so a `--one` child both
serves as the autotune sweep's measurement probe (tools/autotune.py
sets the env knobs per candidate and TPK_TUNING_CACHE=0) and, in
normal runs, automatically benefits from promoted entries; a cache-
sourced resolution lands a `tuning_resolved` health event in this
run's journal.
"""

from __future__ import annotations

import functools
import json
import os
import statistics
import sys
import time

# Persist compiled executables across bench invocations: each metric
# compiles two jitted repeat-count variants at 20-40 s per remote
# compile, which otherwise dominates the run's wall clock. Must run
# before the jax import below (see tpukernels/_cachedir.py).
from tpukernels._cachedir import ensure_compilation_cache

ensure_compilation_cache()

# Resilience layer (stdlib-only, so safe before the jax import): the
# three timeout mechanisms live in watchdog, fault injection in
# faults, and every wedge/partial/invalidation decision is journaled
# as a structured health event (docs/RESILIENCE.md).
from tpukernels.resilience import faults, integrity, journal, watchdog

# Observability layer (also stdlib-only, docs/OBSERVABILITY.md):
# spans are a shared no-op unless TPK_TRACE is set (clean-path stdout
# stays byte-identical — tests/test_obs.py proves it the same way the
# fault layer is proven); metric counters are process-local until the
# end-of-run snapshot lands in the health journal.
from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import scaling as obs_scaling
from tpukernels.obs import trace

# AOT compile layer (stdlib at import too, docs/PERF.md §compile
# discipline): _slope's compile phase routes through its choke point
# so every loop-program compile leaves aot_hit/aot_miss evidence and
# the timing octets call compiled executables, never a cold jit.
# TPK_AOT_CACHE=0 restores the old warm-call compile exactly.
from tpukernels import aot

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# per-benchmark watchdog (tunnel can wedge); env-tunable so the CPU
# chaos suite (tests/test_resilience.py) can drive the REAL timeout ->
# hard-kill -> reclassify path in seconds instead of 12 minutes
_BENCH_TIMEOUT_S = int(os.environ.get("TPK_BENCH_TIMEOUT_S", "600"))
# held back from each child's window for the post-timeout wedge probe
# (90 s) + JSON emission; also the slack callers must add on top of
# TPK_BENCH_DEADLINE_S
_CHILD_GRACE_S = int(os.environ.get("TPK_BENCH_CHILD_GRACE_S", "120"))
# minimum budget left before a metric is still worth starting; must
# exceed the grace reserve or the child's computed window goes
# negative (a child spawned and killed instantly reads as a wedge)
_DEADLINE_FLOOR_S = max(180, _CHILD_GRACE_S + 60)
_REGRESSION_TOL = 0.15  # shared by check_regression and skip-captured
# A capture may exceed its physical ceiling by this much before being
# invalidated: the sgemm ceiling (61333) sits only 0.8% above the
# median of record (60834), so ordinary upward noise on an honest
# near-peak capture would otherwise be thrown away. Drift inflation —
# the failure mode ceilings exist for — measured 19-58% high, far
# outside this band. Documented in BASELINE.md/BASELINE.json;
# tools/promote_baseline.py applies the same epsilon.
_CEILING_EPS = 0.01

_Timeout = watchdog.Timeout  # back-compat alias (tests, callers)


def _with_timeout(fn, seconds=_BENCH_TIMEOUT_S):
    """Run fn() under SIGALRM so a wedged TPU tunnel skips one metric
    instead of hanging the whole round. Soft layer only — see
    tpukernels/resilience/watchdog.py for the semantics."""
    return watchdog.run_with_alarm(fn, seconds, site="bench._with_timeout")


def _timeit(fn, *args, reps=4, warmup=2):
    """Best-of wall seconds/call; fn must return something tiny so the
    np.asarray() materialization forces device completion."""
    for _ in range(warmup):
        np.asarray(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(fn(*args))
        t1 = time.perf_counter()
        best = min(best, t1 - t0)
    return best


def _slope(make_fn, r_small, r_big, samples=5):
    """Marginal seconds per loop iteration.

    make_fn(R) -> (jitted_fn, args) where fn runs R dependent
    iterations on-device. Timing both R values and dividing the
    difference cancels the fixed per-dispatch cost (axon tunnel
    round-trip, host overhead) that a single-call measurement would
    mis-attribute to the kernel.

    The tunnel's fixed cost also JITTERS run to run (observed ~30%
    swings), so one slope sample can be badly off in either
    direction; take the median of several (each from fresh best-of-3
    timings at both R values — cheap, compile is already done) and
    drop non-positive samples from stall-corrupted readings.

    Drift cancellation (added 2026-07-31): the cancel-the-fixed-cost
    argument assumes the fixed cost is STATIONARY across a sample. On
    a freshly recovered tunnel it is not — latency drains downward
    over the first minutes — and with R_small always timed before
    R_big the drift subtracts from every sample's (t_big - t_small)
    in the same direction, so the median inherits the bias instead of
    rejecting it. Observed: post-recovery sgemm captures of 72.7 and
    96.0 TFLOPS against a 61 TFLOPS physical ceiling for the 3-pass
    bf16 kernel (184 TFLOPS measured single-pass peak / 3), while
    stable-link sessions measure 60.8 at the ceiling.

    Ordering tricks (palindrome windows) only cancel drift if the two
    R values' measurement windows are the same length — they are not
    (the big-R call is longer), and best-of-N min-picking pushes each
    window's effective sample time to its END under monotone drift,
    leaving a residual bias proportional to the window-length gap
    with the SAME sign for either polarity. So each sample instead
    times 8 single calls in an interleaved order (s,b,b,s,b,s,s,b),
    records each call's wall-clock MIDPOINT, and least-squares fits
        t = c0 + c1*midpoint + slope*R
    — the time regressor absorbs any linear drift exactly, with no
    symmetry assumptions about call durations. Jitter spikes enter
    one fit at ~1/(4*(r_big-r_small)) weight and the median over
    samples rejects the rest, as before.

    TPK_BENCH_SMOKE=1 collapses the repeat counts so every bench_*
    function can be exercised end-to-end on CPU tiny shapes (the
    returned "metric" is then meaningless) — the regression test that
    keeps unattended chip revalidation from dying on Python bitrot.
    """
    smoke = os.environ.get("TPK_BENCH_SMOKE") == "1"
    if smoke:
        r_small, r_big = 1, 2
    # stderr breadcrumbs bracket each phase so a tunnel wedge is
    # attributable from the watch log. Operand generation/H2D — the
    # prime wedge suspect for stencil3d — runs in the bench_* body
    # BEFORE _slope is entered; the '--one <name> starting' line in
    # __main__ opens that phase and this first line closes it.
    print("# slope: entered (operands built)", file=sys.stderr, flush=True)
    faults.phase_fault("operand")  # no-op without a TPK_FAULT_PLAN
    f_s, a_s = make_fn(r_small)
    f_b, a_b = make_fn(r_big)
    # bench_sgemm.<locals>.make -> "bench_sgemm": the AOT manifest key
    # for each loop program (metric + repeat count select the program)
    label = make_fn.__qualname__.split(".")[0]
    calls = {}
    with trace.span("slope/compile", r_small=r_small, r_big=r_big):
        for r, f, a in ((r_small, f_s, a_s), (r_big, f_b, a_b)):
            print(f"# slope: compiling R={r}", file=sys.stderr,
                  flush=True)
            if aot.enabled() and hasattr(f, "lower"):
                # compile strictly out of the measure path: lower +
                # backend-compile through the AOT choke point (span +
                # aot_hit/aot_miss evidence + compile-wall metrics),
                # then ONE warm execution; the timing octets below
                # call the compiled executable — zero re-trace, zero
                # jit dispatch. TPK_AOT_CACHE=0 keeps the old
                # compile-via-first-call behavior exactly; so does a
                # make_fn returning a plain callable instead of a jit
                # wrapper (the sleep-based estimator tests).
                f = aot.compile_jitted(
                    f"{label}.R{r}", f, a,
                    sources=_slope_sources(label),
                )
            warm = np.asarray(f(*a))  # warm (without AOT: compile+warm)
            # Output-integrity guard on the warm result, strictly
            # outside the timed octets (docs/RESILIENCE.md §output
            # integrity): every loop body reduces through a sum, so a
            # NaN anywhere in R iterations poisons this scalar — the
            # tier-1 tripwire covers the whole loop program — and the
            # first-trust canary cross-checks this metric's kernel
            # against its jnp oracle before a window is spent timing
            # it. Never raises; a failure is journaled + quarantined.
            integrity.guard(
                "bench", _SLOPE_GUARD_KERNELS.get(label), warm,
                # on failure, also invalidate THIS metric's compiled
                # loop programs (manifest keys "bench_<fn>.R<n>@...")
                # — they are the executables that produced the
                # corrupt warm result, not just the kernel's dispatch
                # entries
                invalidate_prefixes=(label + ".",),
            )
            calls[r] = (f, a)
    faults.phase_fault("compile")
    if os.environ.get("TPK_BENCH_PREWARM") == "1":
        # --prewarm mode: both R variants are now in the persistent
        # compilation cache and have executed once; timing would only
        # hold the chip. inf makes the caller's metric arithmetic
        # yield 0.0 — harmless, since --prewarm emits no stdout JSON.
        print("# slope: prewarm complete (compiles cached)",
              file=sys.stderr, flush=True)
        return float("inf")
    print("# slope: timing", file=sys.stderr, flush=True)
    faults.phase_fault("execute")
    if smoke:
        # both R variants built, compiled and executed — that is the
        # smoke coverage; timing µs-scale CPU runs would only flake
        return 1.0
    octet = (r_small, r_big, r_big, r_small,
             r_big, r_small, r_small, r_big)
    ests = []
    min_valid = min(3, samples)
    with trace.span("slope/execute", samples=samples,
                    r_small=r_small, r_big=r_big):
        for attempt in range(3 * samples):
            if len(ests) >= samples:
                break
            rows, durs = [], []
            t_base = time.perf_counter()  # centered time regressor: raw
            # perf_counter values are ~1e5 s and near-constant across
            # the sample, which ill-conditions the fit against the
            # intercept
            for r in octet:
                f, a = calls[r]
                t0 = time.perf_counter()
                np.asarray(f(*a))
                t1 = time.perf_counter()
                rows.append((1.0, (t0 + t1) / 2.0 - t_base, float(r)))
                durs.append(t1 - t0)
            coef, *_ = np.linalg.lstsq(
                np.array(rows), np.array(durs), rcond=None
            )
            if coef[2] > 0:
                ests.append(float(coef[2]))
    obs_metrics.inc("bench.slope_samples_valid", len(ests))
    if len(ests) < min_valid:
        # a median of 1-2 surviving samples is just the single-slope
        # jitter problem again; refuse to report it as a median
        raise RuntimeError(
            f"only {len(ests)} valid slope samples after {3 * samples} "
            f"attempts (tunnel stalls corrupted the rest)"
        )
    return statistics.median(ests)


def bench_sgemm(m=1024):
    from tpukernels.kernels.sgemm import sgemm

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)
    c = jnp.asarray(rng.standard_normal((m, m)), jnp.float32)

    def make(R):
        # beta=0.5 chains each matmul on the previous result (stable:
        # c_n -> 2*A@B) so the loop cannot be hoisted or parallelized.
        def f(a, b, c):
            body = lambda i, cc: sgemm(1.0, a, b, 0.5, cc)
            return jnp.sum(lax.fori_loop(0, R, body, c))

        return jax.jit(f), (a, b, c)

    t = _slope(make, 50, 750)
    return 2.0 * m**3 / t / 1e9


@functools.lru_cache(maxsize=None)
def _normal_generator(shape):
    """One jitted generator per shape, cached at module level: the
    PRNGKey is a traced ARGUMENT, and the jit wrapper itself must be
    shared too — a fresh jax.jit(lambda ...) per call keys the jit
    cache per wrapper, so same-shape operands (saxpy_stream's x and y)
    would each pay the ~20-40 s cold remote compile anyway."""
    return jax.jit(lambda k: jax.random.normal(k, shape, jnp.float32))


def _device_normal(seed, shape):
    """Standard-normal input generated ON DEVICE (jit'd jax.random).

    The large-array benches used host RNG + jnp.asarray, which streams
    the whole operand through the axon tunnel (stencil3d: 216 MB,
    saxpy_stream: 512 MB). The flapping tunnel wedged mid-stencil3d in
    two consecutive healthy windows (03:17 and 07:16 on 2026-07-31)
    right at that H2D step, and a multi-hundred-MB transfer is also
    minutes of setup wall-clock per metric. Device-side generation
    makes operand setup a ~µs program launch; input VALUES don't
    matter for slope timing (no golden check here), only shape/dtype.
    """
    return _normal_generator(tuple(shape))(jax.random.PRNGKey(seed))


def bench_stencil(n=4096):
    from tpukernels.kernels.stencil import jacobi2d

    x = _device_normal(1, (n, n))

    def make(R):
        return jax.jit(lambda x: jnp.sum(jacobi2d(x, R))), (x,)

    t = _slope(make, 20, 320)
    return float(n) * n / t / 1e6


def bench_stencil3d(n=384):
    from tpukernels.kernels.stencil import jacobi3d

    x = _device_normal(6, (n, n, n))

    def make(R):
        return jax.jit(lambda x: jnp.sum(jacobi3d(x, R))), (x,)

    t = _slope(make, 8, 64)
    return float(n) ** 3 / t / 1e6


def bench_saxpy_stream(n=1 << 26):
    """Streaming SAXPY: working set (512 MiB) far exceeds VMEM, so this
    measures sustained HBM bandwidth, unlike bench_saxpy's VMEM-resident
    N=2^20 config of record."""
    from tpukernels.kernels.vector_add import saxpy

    x = _device_normal(5, (n,))
    y = _device_normal(50, (n,))

    def make(R):
        def f(x, y):
            body = lambda i, yy: saxpy(1e-3, x, yy)
            return jnp.sum(lax.fori_loop(0, R, body, y)[:1])

        return jax.jit(f), (x, y)

    t = _slope(make, 10, 110)
    return 3.0 * 4.0 * n / t / 1e9


def bench_nbody(n=65536):
    from tpukernels.kernels.nbody import nbody_step

    rng = np.random.default_rng(2)
    args = tuple(
        jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(6)
    ) + (jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),)

    def make(R):
        f = jax.jit(lambda *a: jnp.sum(nbody_step(*a, steps=R)[0]))
        return f, args

    t = _slope(make, 1, 6)
    return float(n) * n / t / 1e9


def bench_scan_hist(n=1 << 22):
    # the combined wrapper resolves TPK_SCANHIST_FUSE (off = the two
    # proven kernels, exactly the old metric path; on = the fused
    # single-pass kernel), so the autotuner sweeps the fuse axis
    # through this real metric path (docs/TUNING.md)
    from tpukernels.kernels.scan_histogram import scan_histogram

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.integers(0, 256, n), jnp.int32)

    def make(R):
        def f(x):
            def body(i, carry):
                xc, acc = carry
                s, h = scan_histogram(xc, 256)
                # parity of a data-dependent sum; xor keeps values in
                # [0,256) while chaining each iteration on the last
                acc = (acc + s[-1] + h[0]) & 1
                return (xc ^ acc, acc)

            xc, acc = lax.fori_loop(0, R, body, (x, jnp.int32(0)))
            return jnp.sum(xc[:1]) + acc

        return jax.jit(f), (x,)

    t = _slope(make, 2, 22)
    return float(n) / t / 1e6


def bench_saxpy(n=1 << 20):
    from tpukernels.kernels.vector_add import saxpy

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal(n), jnp.float32)
    y = jnp.asarray(rng.standard_normal(n), jnp.float32)

    def make(R):
        def f(x, y):
            body = lambda i, yy: saxpy(1e-3, x, yy)
            return jnp.sum(lax.fori_loop(0, R, body, y)[:1])

        return jax.jit(f), (x, y)

    # ~1.7 us/iter: need a large R delta so the marginal signal (~34 ms)
    # dominates run-to-run jitter in the ~65 ms fixed dispatch cost.
    t = _slope(make, 1000, 21000)
    return 3.0 * 4.0 * n / t / 1e9  # read x, read y, write y


def _tpu_alive(timeout_s=180, attempts=6, retry_wait_s=120):
    """Probe backend liveness in a subprocess with a hard kill.

    SIGALRM cannot interrupt a hung C-level PJRT init (signal handlers
    only run between Python bytecodes), so a dead axon tunnel would
    hang this process *before* any per-benchmark watchdog — observed
    in practice. A subprocess is killable from outside regardless.
    Patience is deliberately high (~30 min worst case): tunnel outages
    of 10+ minutes have been observed to recover, and the compilation
    cache makes the bench itself cheap once the chip is back.

    TPK_BENCH_PROBE_ATTEMPTS caps the attempts: a watcher-fired queue
    just probed the tunnel healthy moments ago, so a failing probe
    HERE means it already re-wedged — burning the default ~30 min of
    patience inside the queue would eat the next flap window from
    under the watcher that is better placed to wait it out."""
    import subprocess

    cap = os.environ.get("TPK_BENCH_PROBE_ATTEMPTS")
    if cap is not None:
        try:
            attempts = int(cap)
        except ValueError:
            attempts = 0
        if attempts <= 0:
            raise ValueError(
                f"TPK_BENCH_PROBE_ATTEMPTS={cap!r}: expected a positive "
                "integer"
            )
    wait = os.environ.get("TPK_BENCH_PROBE_WAIT_S")
    if wait is not None:
        # chaos tests compress the patience loop to seconds; operators
        # can likewise tune the flap-recovery wait without code edits
        retry_wait_s = float(wait)

    def probe_once(attempt):
        forced = faults.probe_outcome()  # None without a TPK_FAULT_PLAN
        if forced is not None:
            journal.emit(
                "probe", attempt=attempt, outcome=forced, injected=True
            )
            return "alive" if forced == "ok" else "retry"
        try:
            r = subprocess.run(
                [
                    sys.executable,
                    "-c",
                    "import jax; print('platform=' +"
                    " jax.devices()[0].platform)",
                ],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            journal.emit("probe", attempt=attempt, outcome="hang")
            return "retry"
        # require a TPU-class backend: a CPU fallback would silently
        # report CPU numbers as TPU GFLOPS
        if r.returncode == 0 and (
            "platform=tpu" in r.stdout or "platform=axon" in r.stdout
        ):
            journal.emit("probe", attempt=attempt, outcome="alive")
            return "alive"
        if (
            r.returncode == 0
            and "platform=" in r.stdout
            and not os.environ.get("PALLAS_AXON_POOL_IPS")
        ):
            # clean non-TPU answer with no TPU configured on this
            # box: waiting cannot conjure one — exit fast. When
            # the pool var IS set, a clean CPU answer can be a
            # fail-fast tunnel outage (jax falls back silently),
            # which recovers — that case keeps the retry patience,
            # like hangs and errors do.
            print(
                "# no TPU backend (" + r.stdout.strip() + ")",
                file=sys.stderr,
            )
            journal.emit(
                "probe", attempt=attempt, outcome="no_tpu_configured"
            )
            return "dead"
        journal.emit(
            "probe", attempt=attempt, outcome="error",
            returncode=r.returncode,
        )
        return "retry"

    return watchdog.patient_probe(
        probe_once, attempts, retry_wait_s, label="TPU liveness probe"
    )


# The full metric surface, single source of truth: main() runs it and
# tests assert BASELINE.json's "measured" block covers it — a new
# bench_* added here without a measured median fails the suite instead
# of silently escaping the regression gate.
#
# ORDER = capture order under the flapping tunnel: headline canary
# first (the gate requires it fresh), then cheapest-setup /
# fastest-compiling metrics, so a 2-25 min healthy window banks the
# most evidence before a wedge. stencil3d LAST: it wedged the tunnel
# mid-metric in two consecutive windows (2026-07-31 03:17 and 07:16)
# and must not eat the window from under the five metrics after it.
BENCH_METRICS = (
    ("sgemm_gflops", bench_sgemm),
    ("saxpy_gb_s", bench_saxpy),
    ("scan_hist_melem_s", bench_scan_hist),
    ("nbody_ginter_s", bench_nbody),
    ("stencil2d_mcells_s", bench_stencil),
    ("saxpy_stream_gb_s", bench_saxpy_stream),
    ("stencil3d_mcells_s", bench_stencil3d),
)


def _is_measurement(v):
    """A detail entry that is a real measured number — not None, not a
    bool, and not the string payloads of the tunnel-down error line
    (details = {"error": ..., "last_persisted_artifact": ...}), which
    must never count as evidence."""
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _iter_bench_artifacts(root=None):
    """Yield (abspath, parsed_record) for docs/logs/bench_*.json,
    newest first by FILENAME timestamp — the writer embeds a sortable
    stamp (bench_%Y-%m-%d_%H%M%S.json, tools/tpu_revalidate.sh) and
    these files are committed; git does not preserve mtimes, so after
    a clone/checkout mtime order is arbitrary. Unparseable files are
    skipped. Single scanner shared by the pointer path and the union
    gate so they cannot disagree about what evidence exists."""
    import glob

    if root is None:
        root = os.path.dirname(os.path.abspath(__file__))
    for p in sorted(
        glob.glob(os.path.join(root, "docs", "logs", "bench_*.json")),
        key=os.path.basename,
        reverse=True,
    ):
        try:
            with open(p) as f:
                rec = json.loads(f.read().strip() or "null")
        except (OSError, ValueError):
            continue
        if isinstance(rec, dict):
            yield p, rec


def _artifact_stamp(relpath):
    """Unix timestamp embedded in a bench artifact's FILENAME, or None
    when the path doesn't carry one (the writer's stamp is the only
    portable ordering — git does not preserve mtimes)."""
    import datetime

    if not isinstance(relpath, str):
        return None
    try:
        return datetime.datetime.strptime(
            os.path.basename(relpath), "bench_%Y-%m-%d_%H%M%S.json"
        ).timestamp()
    except ValueError:
        return None


def _latest_persisted_artifact(root=None):
    """Newest docs/logs/bench_*.json holding at least one real
    measurement, as {"path": ..., "line": {...}} — or None. Only
    consulted on the tunnel-unreachable path, where it is reported as
    a POINTER to earlier evidence, never as the run's own
    measurement."""
    if root is None:
        root = os.path.dirname(os.path.abspath(__file__))
    for p, rec in _iter_bench_artifacts(root):
        # a wedged run with a null headline but captured detail
        # metrics (e.g. sgemm wedged, stencil survived) is still
        # evidence worth pointing at; a tunnel-down error line
        # (string-valued details, no numbers) is not
        if _is_measurement(rec.get("value")) or any(
            _is_measurement(v) for v in (rec.get("details") or {}).values()
        ):
            return {"path": os.path.relpath(p, root), "line": rec}
    return None


# Per-metric kernel sources for the git-aware evidence cut-off below.
# tests/test_bench_utils.py asserts every BENCH_METRICS name has an
# entry, so a new metric cannot silently get the weaker bench.py-only
# epoch.
_METRIC_KERNEL_SOURCES = {
    "sgemm_gflops": ("tpukernels/kernels/sgemm.py",),
    "saxpy_gb_s": ("tpukernels/kernels/vector_add.py",),
    "saxpy_stream_gb_s": ("tpukernels/kernels/vector_add.py",),
    "scan_hist_melem_s": (
        "tpukernels/kernels/scan.py",
        "tpukernels/kernels/histogram.py",
        "tpukernels/kernels/scan_histogram.py",
    ),
    "nbody_ginter_s": ("tpukernels/kernels/nbody.py",),
    "stencil2d_mcells_s": ("tpukernels/kernels/stencil.py",),
    "stencil3d_mcells_s": ("tpukernels/kernels/stencil.py",),
}


# bench loop-program label -> the registry kernel its integrity
# canary validates (_slope's guard; docs/RESILIENCE.md §output
# integrity). Unknown labels (tests driving _slope with their own
# make_fn) guard with kernel=None: tier-1 tripwire only.
_SLOPE_GUARD_KERNELS = {
    "bench_sgemm": "sgemm",
    "bench_saxpy": "vector_add",
    "bench_saxpy_stream": "vector_add",
    "bench_stencil": "stencil2d",
    "bench_stencil3d": "stencil3d",
    "bench_scan_hist": "scan_histogram",
    "bench_nbody": "nbody",
}


def _slope_sources(label):
    """Git-epoch sources for one bench loop program's AOT manifest
    entry (`label` = the bench_* function name): the metric's kernel
    sources plus bench.py itself — the loop body lives here — i.e.
    the same files whose commits already gate this metric's persisted
    evidence. Unknown labels (tests driving _slope with their own
    make_fn) fall back to bench.py alone."""
    metric = {fn.__name__: n for n, fn in BENCH_METRICS}.get(label)
    return _METRIC_KERNEL_SOURCES.get(metric, ()) + ("bench.py",)


def _git_head(root=None):
    """HEAD sha stamped into the emitted JSON line so every persisted
    artifact records which code produced it; None outside a repo.
    Same resolver the health journal stamps events with, so artifacts
    and journal lines from one session can be correlated."""
    return journal.git_head(root)


def _last_commit_ts(root, paths):
    """Committer timestamp (unix) of the newest commit touching any of
    `paths`, or None when git/history is unavailable — non-repo roots
    (test tmp dirs) then keep the wall-clock-only window."""
    import subprocess

    try:
        r = subprocess.run(
            ["git", "-C", root, "log", "-1", "--format=%ct", "--", *paths],
            capture_output=True,
            text=True,
            timeout=30,
        )
    except Exception:
        return None
    out = r.stdout.strip()
    if r.returncode != 0 or not out:
        return None
    try:
        return int(out.splitlines()[-1])
    except ValueError:
        return None


def _metric_evidence_epochs(root):
    """{metric: unix_ts_or_None} — the evidence cut-off per metric: the
    committer time of the newest commit touching that metric's kernel
    sources or bench.py itself. An artifact stamped before this was
    measured on pre-change code and must not satisfy the union gate
    for that metric (the 24 h window alone is wall-clock: a stencil
    regression committed at 08:00 would otherwise pass a 09:00 gate on
    03:18 evidence). Committer time vs the artifact's local-time
    filename stamp is a consistent comparison on this box (UTC).

    Capture discipline this implies: commit kernel/bench changes
    BEFORE capturing evidence, and keep artifact-persisting commits
    free of kernel/bench.py edits — a snapshot commit bundling
    artifacts WITH such an edit retroactively rejects those artifacts.
    That direction is chosen deliberately: the failure mode is a
    visible, retryable rc 2 at the union gate (re-measure), never a
    silent pass on pre-change evidence."""
    cache = {}
    out = {}
    for name, _fn in BENCH_METRICS:
        paths = _METRIC_KERNEL_SOURCES.get(name, ()) + ("bench.py",)
        if paths not in cache:
            cache[paths] = _last_commit_ts(root, paths)
        out[name] = cache[paths]
    return out


def _recent_captured_metrics(root=None, max_age_h=24.0, rejected=None,
                             epochs=None):
    """Union of measured per-metric values from docs/logs/bench_*.json
    artifacts whose FILENAME timestamp is within `max_age_h` of now
    (newest artifact wins per metric). Returns {metric: (value,
    relpath)}.

    Powers two flap-cycle accumulators (the tunnel has been observed
    to serve ~2-25 healthy minutes between wedges, so one window
    rarely fits all seven metrics):
      - TPK_BENCH_SKIP_CAPTURED=1: spend a short healthy window only
        on metrics with no persisted evidence yet;
      - --check-regression --union-persisted: let evidence accumulated
        across several windows satisfy the gate together.
    The window is both wall-clock (max_age_h) AND git-aware: per
    metric, artifacts stamped before the last commit touching that
    metric's kernel sources or bench.py are rejected (see
    _metric_evidence_epochs) — evidence predating a same-day kernel
    change must be re-measured, not carried.

    Rejections are never silent (ADVICE r5): each one prints a stderr
    note naming the metric, the artifact and the blocking commit
    timestamp, emits an `epoch_rejected` journal event, and — when the
    caller passes a `rejected` dict — is recorded there as
    {metric: (artifact_relpath, blocking_commit_ts)} so
    check_regression can distinguish "epoch-rejected" from "absent".

    `epochs` lets the union gate pass its already-computed
    _metric_evidence_epochs table in (it needs the same table for the
    carried re-check) instead of forking git twice per gate run."""
    if root is None:
        root = os.path.dirname(os.path.abspath(__file__))
    now_ts = time.time()
    if epochs is None:
        epochs = _metric_evidence_epochs(root)
    out = {}
    # _iter_bench_artifacts yields newest first; first writer wins =
    # newest value per metric
    for p, rec in _iter_bench_artifacts(root):
        stamp_ts = _artifact_stamp(p)
        if stamp_ts is None:
            continue
        age_h = (now_ts - stamp_ts) / 3600.0
        if not (0 <= age_h <= max_age_h):
            # future-stamped files are clock skew/testing noise, not
            # evidence
            continue
        for name, value in (rec.get("details") or {}).items():
            if not (_is_measurement(value) and name not in out):
                continue
            epoch = epochs.get(name)
            if epoch is not None and stamp_ts < epoch:
                # measured on pre-change code: a commit touching this
                # metric's kernel (or bench.py) postdates the artifact
                rel = os.path.relpath(p, root)
                print(
                    f"# epoch-rejected: {name} from {rel} (artifact "
                    f"predates commit ts {epoch} touching its sources)",
                    file=sys.stderr,
                )
                journal.emit(
                    "epoch_rejected", metric=name, artifact=rel,
                    blocking_commit_ts=epoch,
                )
                if rejected is not None and name not in rejected:
                    rejected[name] = (rel, epoch)
                continue
            out[name] = (value, os.path.relpath(p, root))
    return out


def _run_one_subprocess(name: str, timeout_s: float):
    """Run one metric via `bench.py --one <name>` in a killable child.

    The in-process SIGALRM watchdog (_with_timeout) cannot interrupt a
    hung C-level PJRT call — observed 2026-07-31: the tunnel answered a
    liveness probe, then wedged ~2 min later mid-suite, and SIGALRM
    never fired. A subprocess is killable from outside regardless of
    where it hangs. Returns (value_or_None, status) with status in
    {"ok", "timeout", "error", "parse"}; stderr passes through so the
    child's progress lines land in the caller's log."""
    import subprocess

    r, status = watchdog.kill_after(
        [sys.executable, os.path.abspath(__file__), "--one", name],
        timeout_s,
        site=f"bench --one {name}",
        stdout=subprocess.PIPE,
        text=True,
    )
    if status == "timeout":
        return None, "timeout"
    if r.returncode != 0:
        return None, "error"
    try:
        last = r.stdout.strip().splitlines()[-1]
        return json.loads(last)["value"], "ok"
    except Exception:
        return None, "parse"


def main():
    # clock starts BEFORE the liveness probe: the probe's recovery
    # patience (~28 min worst case) must come out of the same budget
    # the caller's outer timeout covers, or probe + metrics together
    # can outlast the caller and get killed mid-run after all
    t0 = time.monotonic()
    results = {}
    journal.emit(
        "run_start", mode="suite",
        deadline_s=float(os.environ.get("TPK_BENCH_DEADLINE_S", "4800")),
        fault_plan_active=faults.active(),
    )
    # hardware attribution stamp (docs/OBSERVABILITY.md §scaling). The
    # suite parent must never touch jax.devices() itself — that would
    # initialize the backend this very function is about to probe in a
    # killable subprocess — so this stamps the env-derived inventory;
    # the --one children stamp the jax-backed one.
    obs_scaling.emit_inventory("bench")
    with trace.span("probe/liveness"):
        alive = _tpu_alive()
    if not alive:
        journal.emit(
            "run_end", outcome="unreachable",
            reason="TPU backend unreachable (tunnel down)",
        )
        details = {"error": "TPU backend unreachable (tunnel down)"}
        prior = _latest_persisted_artifact()
        if prior is not None:
            # honesty note, not a substitute measurement: the headline
            # stays null (nothing was measured NOW), but if a
            # watcher-fired queue captured numbers earlier in this
            # flap cycle, point the reader at that committed artifact
            # instead of leaving "null" to read as "no evidence
            # exists" (see tools/tpu_revalidate.sh step 1)
            details["last_persisted_artifact"] = prior
        print(
            json.dumps(
                {
                    "metric": "sgemm_gflops_per_chip",
                    "value": None,
                    "unit": "GFLOPS",
                    "vs_baseline": None,
                    "details": details,
                    "git_head": _git_head(),
                }
            )
        )
        return
    # One killable subprocess per metric (order = BENCH_METRICS, so the
    # headline sgemm number is captured FIRST): if the tunnel wedges
    # mid-run we emit every metric captured so far instead of hanging
    # until some outer timeout discards the whole run — that failure
    # mode produced three consecutive null BENCH artifacts. After a
    # timeout, one quick liveness re-probe decides "slow" vs "wedged";
    # wedged skips the remaining metrics immediately rather than
    # burning a full watchdog window on each.
    #
    # Whole-run deadline, measured from main() entry (t0 above, so it
    # absorbs however long the startup _tpu_alive probe took):
    # worst-case per-metric deadlines alone sum past any sane caller
    # timeout (7 x 720 s), and an OUTER kill (tools/tpu_revalidate.sh's
    # `timeout`, the driver's bound) discards the whole run with no
    # JSON line — the exact failure the per-metric isolation exists to
    # prevent — while orphaning the in-flight --one child on the TPU.
    # Enforcing the budget HERE means the JSON line always gets out
    # and children are always reaped; metrics past the deadline report
    # None. Callers must allow > TPK_BENCH_DEADLINE_S end to end.
    deadline = t0 + float(os.environ.get("TPK_BENCH_DEADLINE_S", "4800"))
    # _CHILD_GRACE_S of each child's window is held back for the
    # post-timeout wedge probe (90 s) + JSON emission, so main() cannot
    # overrun the deadline by more than that reserve. Callers' outer
    # timeouts must still allow TPK_BENCH_DEADLINE_S plus that margin.
    metrics = list(BENCH_METRICS)
    only = os.environ.get("TPK_BENCH_ONLY")
    if only:
        # chaos-test / targeted-re-measure knob: run only the named
        # metrics. The emitted line then has partial coverage, which
        # the union gate reports as rc 2 — this never weakens a gate.
        want = [n.strip() for n in only.split(",") if n.strip()]
        unknown = [n for n in want if n not in dict(BENCH_METRICS)]
        if unknown:
            raise ValueError(
                f"TPK_BENCH_ONLY names unknown metrics {unknown}; known: "
                + ", ".join(n for n, _f in BENCH_METRICS)
            )
        metrics = [(n, f) for n, f in metrics if n in want]
        journal.emit("metrics_restricted", only=want)
    carried = {}
    if os.environ.get("TPK_BENCH_SKIP_CAPTURED") == "1":
        # watcher-fired queues set this: a flap window too short for
        # all seven metrics should be spent on the ones with no
        # persisted evidence yet. Skipped metrics are ABSENT from
        # "details" (this run did not measure them) and listed under
        # "carried" with the artifact each value came from; the
        # queue's gate runs --union-persisted to judge the union.
        # Two metrics are never skipped:
        #   - the headline (sgemm): a fresh canary every attempt, so a
        #     same-day code change can't ride entirely on pre-change
        #     artifacts;
        #   - anything whose carried value is already below tolerance:
        #     freezing a degraded measurement would make every retry
        #     fail on the one metric it refuses to re-run.
        prior = _recent_captured_metrics()
        known = dict(BENCH_METRICS)
        prior_ratios = _ratios_vs_baseline(
            {n: v for n, (v, _p) in prior.items()}, _load_baseline()
        )
        for n, (v, p) in prior.items():
            if n not in known or n == "sgemm_gflops":
                continue
            if prior_ratios.get(n, 1.0) < 1.0 - _REGRESSION_TOL:
                continue
            carried[n] = (v, p)
        if carried:
            metrics = [(n, f) for n, f in metrics if n not in carried]
            print(
                "# skip-captured: "
                f"{sorted(carried)} have persisted evidence <24h old; "
                f"measuring {[n for n, _ in metrics]}",
                file=sys.stderr,
            )
            journal.emit(
                "skip_captured",
                carried=sorted(carried),
                measuring=[n for n, _f in metrics],
            )
    wedged = False
    # Physical upper bounds (BASELINE.json "ceilings"): a capture
    # ABOVE its ceiling is a measurement artifact — the 2026-07-31
    # drift-inflated sgemm readings (72.7 / 96.0 TFLOPS vs the bf16_3x
    # kernel's ~61 TFLOPS bound) — and must be invalidated at the
    # source so no persisted artifact ever carries it into the union
    # or a baseline promotion. Uses the established invalidation
    # convention: [original_value, reason] under "invalidated", null
    # where the value stood (both evidence scanners ignore it).
    ceilings = _load_baseline().get("ceilings") or {}
    invalidated = {}
    for name, _fn in metrics:
        remaining = deadline - time.monotonic()
        if wedged or remaining < _DEADLINE_FLOOR_S:
            if not wedged and remaining < _DEADLINE_FLOOR_S:
                print(
                    f"# whole-run deadline reached before {name} - "
                    "emitting partial results",
                    file=sys.stderr,
                )
                journal.emit(
                    "deadline_reached", before_metric=name,
                    remaining_s=round(remaining, 1),
                )
                wedged = True  # skip the rest, same as the wedge path
            results[name] = None
            journal.emit(
                "partial_result", metric=name,
                reason="skipped (wedged or deadline)",
            )
            continue
        # suite/<metric> wraps the whole killable child (spawn +
        # measure + reap); the child's own measure/<metric> span times
        # just the measurement, so their difference is isolation cost
        with trace.span(f"suite/{name}", metric=name):
            value, status = _run_one_subprocess(
                name,
                min(_BENCH_TIMEOUT_S + _CHILD_GRACE_S,
                    remaining - _CHILD_GRACE_S),
            )
        obs_metrics.inc(
            "bench.metric_ok" if value is not None
            else "bench.metric_failed"
        )
        ceiling = ceilings.get(name)
        if (
            value is not None
            and _is_measurement(ceiling)
            and value > ceiling * (1.0 + _CEILING_EPS)
        ):
            # > ceiling*(1+eps) is drift, not noise; a capture INSIDE
            # the epsilon band is kept (_CEILING_EPS rationale above).
            # The raw value stays in the artifact under "invalidated".
            print(
                f"# {name}: {value} exceeds the physical ceiling "
                f"{ceiling} (+{_CEILING_EPS:.0%} tolerance) - "
                "invalidated as drift-suspect (see BASELINE.md "
                "methodology)",
                file=sys.stderr,
            )
            journal.emit(
                "invalidated", metric=name, value=value, ceiling=ceiling,
                epsilon=_CEILING_EPS,
            )
            invalidated[name] = [value, f"exceeds ceiling {ceiling}"]
            value = None
        results[name] = value
        if value is not None:
            print(f"# {name}: {value}", file=sys.stderr)
        else:
            print(f"# {name} FAILED ({status})", file=sys.stderr)
            journal.emit("metric_failed", metric=name, status=status)
        sys.stderr.flush()
        if status == "timeout":
            # one quick liveness re-probe decides slow vs wedged; the
            # semantics live in watchdog.classify_timeout
            verdict = watchdog.classify_timeout(
                _tpu_alive(timeout_s=90, attempts=1), metric=name
            )
            if verdict == "wedged":
                print(
                    "# tunnel wedged mid-bench - emitting partial results",
                    file=sys.stderr,
                )
                wedged = True

    headline = results.get("sgemm_gflops")
    ratios = _ratios_vs_baseline(results, _load_baseline())
    vs = ratios.get("sgemm_gflops")

    line = {
        "metric": "sgemm_gflops_per_chip",
        "value": headline,
        "unit": "GFLOPS",
        # a wedged/invalidated headline must read as NOT MEASURED
        # (null), never as "exactly on baseline" (1.0); the 1.0
        # placeholder survives only for a measured headline with no
        # baseline row to divide by
        "vs_baseline": (
            vs if vs is not None else (1.0 if headline is not None else None)
        ),
        "details": results,
        "vs_measured": ratios,
        "git_head": _git_head(),
    }
    if invalidated:
        line["invalidated"] = invalidated
    if carried:
        # prior-window evidence (value, source artifact) — NOT this
        # run's measurements; details/value above are fresh-only
        line["carried"] = {n: list(v) for n, v in carried.items()}
    failed = [n for n, v in results.items() if v is None]
    if failed:
        # a wedge cut this run short; if earlier flap windows
        # captured the missing metrics, point the reader (the judge
        # reads this line as the round artifact) at that evidence —
        # clearly labeled, never merged into details/value
        prior = {
            n: list(v)
            for n, v in _recent_captured_metrics().items()
            if n in failed
        }
        if prior:
            line["prior_evidence"] = prior
    journal.emit(
        "run_end",
        outcome="wedged_partial" if wedged else "complete",
        measured=sorted(n for n, v in results.items() if v is not None),
        failed=failed,
        invalidated=sorted(invalidated),
        carried=sorted(carried),
    )
    print(json.dumps(line))


def _ratios_vs_baseline(results: dict, baseline: dict) -> dict:
    """Per-metric measured/baseline ratios for the vs_measured block.

    Per-metric precedence: a reference-published number (none exist
    today — BASELINE.json "published" is {}) overrides this repo's
    measured-on-chip median for THAT metric only, so one published
    entry can't silently strip the regression gate from every other
    metric. `is not None`, not truthiness, on the result: a metric
    that measured 0.0 must enter the table (as ratio 0.0) so
    check_regression flags it instead of it vanishing from the gate.
    """
    base_tbl = {
        **(baseline.get("measured") or {}),
        **(baseline.get("published") or {}),
    }
    return {
        name: round(results[name] / base_tbl[name], 3)
        for name in results
        if results.get(name) is not None
        and isinstance(base_tbl.get(name), (int, float))
        and not isinstance(base_tbl.get(name), bool)
        and base_tbl.get(name)
    }


def _load_baseline() -> dict:
    try:
        with open(
            os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "BASELINE.json"
            )
        ) as f:
            return json.load(f)
    except Exception:
        return {}


def check_regression(
    json_line: str,
    tolerance: float = _REGRESSION_TOL,
    union_persisted: bool = False,
    root=None,
) -> int:
    """Gate helper for tools/tpu_revalidate.sh: given bench.py's JSON
    output line, judge it against the BASELINE.json "measured"
    medians. Metrics the baseline lacks pass through.

    Exit codes (the watcher's retry loop keys on them):
      0 — every required metric covered and within `tolerance`;
      1 — DETERMINISTIC failure: a metric measured more than
          `tolerance` below baseline, or the line was judged with the
          wrong gate mode. Retrying without a code change is useless.
      2 — INSUFFICIENT COVERAGE: a metric has no value (wedged child,
          null headline, evidence aged out). Nothing regressed —
          another healthy window can fix it, so it is retryable.
    A run with both kinds of failure returns 1 (the regression is the
    more actionable fact).

    union_persisted: judge the UNION of this line's fresh details,
    the line's own carried block (decision-time evidence, immune to
    artifacts aging past the window between skip decision and gate),
    and every persisted artifact <24h old (newest wins per metric) —
    the watcher-fired queue's mode, where evidence accumulates across
    several short flap windows and no single run holds all seven
    metrics. Every BENCH_METRICS name must be covered and within
    tolerance for the union to pass, and the sgemm headline must be
    fresh (measured by THIS run)."""
    rec = json.loads(json_line)
    if rec.get("carried") and not union_persisted:
        # a skip-captured line's details hold only the freshly
        # measured subset; judging it without the union would quietly
        # shrink the gate to 1-2 metrics (pre-skip, details always
        # carried all seven names, so full coverage was implicit)
        print(
            "REGRESSION: line has carried metrics - judge it with "
            "--union-persisted, not the single-run gate"
        )
        return 1
    regressed = []  # rc 1: measured and too slow
    missing = []    # rc 2: not measured at all
    if union_persisted:
        gate_root = root or os.path.dirname(os.path.abspath(__file__))
        fresh = {
            n: v
            for n, v in (rec.get("details") or {}).items()
            if _is_measurement(v)
        }
        rejected = {}  # metric -> (artifact, blocking_commit_ts)
        epochs = _metric_evidence_epochs(gate_root)
        merged = {
            n: v
            for n, (v, _p) in _recent_captured_metrics(
                root, rejected=rejected, epochs=epochs
            ).items()
        }
        for n, vp in (rec.get("carried") or {}).items():
            # ["value", "path"] pairs captured at the skip DECISION —
            # counting them here pins the evidence window to that
            # moment, so a 23.5h-old artifact can't age out during
            # the 40-80 min the fresh metrics take to measure.
            # The git-epoch filter is RE-APPLIED at gate time
            # (ADVICE r5): a commit landing between the skip decision
            # and the gate invalidates the carried artifact for that
            # metric exactly as it would a persisted one — the window
            # pin covers wall-clock aging only, never code changes.
            v = vp[0] if isinstance(vp, (list, tuple)) and vp else None
            p = vp[1] if isinstance(vp, (list, tuple)) and len(vp) > 1 else None
            if not _is_measurement(v):
                continue
            epoch = epochs.get(n)
            stamp = _artifact_stamp(p)
            if (
                epoch is not None
                and stamp is not None
                and stamp < epoch
            ):
                # same "never silent" contract as the persisted-artifact
                # filter: the gate decision must be reconstructable from
                # stderr and the health journal
                print(
                    f"# epoch-rejected: {n} carried from {p} (artifact "
                    f"predates commit ts {epoch} touching its sources)",
                    file=sys.stderr,
                )
                journal.emit(
                    "epoch_rejected", metric=n, artifact=p,
                    blocking_commit_ts=epoch, carried=True,
                )
                if n not in rejected:
                    rejected[n] = (p, epoch)
                continue
            merged.setdefault(n, v)
        merged.update(fresh)
        ratios = _ratios_vs_baseline(merged, _load_baseline())
        # the headline must be FRESH — main()'s skip-captured branch
        # always re-measures sgemm as a canary, and the gate has to
        # enforce that: a union where sgemm rides on a pre-change
        # artifact would pass a same-day kernel regression whose
        # fresh canary wedged or errored
        if "sgemm_gflops" not in fresh:
            missing.append(
                "sgemm_gflops: FAILED (headline not measured by THIS "
                "run; the union may not carry the canary)"
            )
        for name, _fn in BENCH_METRICS:
            if merged.get(name) is None:
                if name in rejected:
                    # distinguish "evidence exists but predates a code
                    # change" from "never captured": the fix for the
                    # first is re-measuring, not waiting for a window
                    art, ts = rejected[name]
                    missing.append(
                        f"{name}: FAILED (epoch-rejected: {art} predates "
                        f"commit ts {ts} touching its sources - "
                        "re-measure on current code)"
                    )
                else:
                    missing.append(
                        f"{name}: FAILED (no value in any artifact <24h)"
                    )
            elif name in ratios and ratios[name] < 1.0 - tolerance:
                regressed.append(
                    f"{name}: {ratios[name]:.3f}x of measured baseline"
                )
        if regressed or missing:
            print(
                "REGRESSION over persisted union (tolerance "
                f"{tolerance:.0%}):"
            )
            for b in regressed + missing:
                print("  " + b)
            return 1 if regressed else 2
        print(f"regression check OK over persisted union: {ratios}")
        return 0
    if rec.get("value") is None:
        print("REGRESSION: headline value is null (bench did not run)")
        return 2
    for name, ratio in (rec.get("vs_measured") or {}).items():
        if ratio < 1.0 - tolerance:
            regressed.append(f"{name}: {ratio:.3f}x of measured baseline")
    for name, v in (rec.get("details") or {}).items():
        if v is None:
            missing.append(f"{name}: FAILED (no value)")
    if regressed or missing:
        print("REGRESSION vs BASELINE.json measured (tolerance "
              f"{tolerance:.0%}):")
        for b in regressed + missing:
            print("  " + b)
        return 1 if regressed else 2
    print(f"regression check OK: {rec.get('vs_measured')}")
    return 0


if __name__ == "__main__":
    # A bench CLI run journals health events by default (library
    # imports stay silent — journaling keys off TPK_HEALTH_JOURNAL).
    # setdefault into os.environ so --one/--prewarm children inherit
    # the SAME file and a whole session lands in one journal; set
    # TPK_HEALTH_JOURNAL=0 to disable.
    os.environ.setdefault("TPK_HEALTH_JOURNAL", journal.default_path())
    if len(sys.argv) > 1 and sys.argv[1] == "--check-regression":
        # stdin: the JSON line a prior `python bench.py` run printed
        sys.exit(
            check_regression(
                sys.stdin.read().strip(),
                union_persisted="--union-persisted" in sys.argv[2:],
            )
        )
    if len(sys.argv) > 1 and sys.argv[1] in ("--prewarm", "--one"):
        # both modes REQUIRE a metric name: a bare invocation must
        # error, not fall through to main() and run the full suite
        # (holding the chip for up to TPK_BENCH_DEADLINE_S and, for
        # --prewarm, emitting the very JSON line the mode promises
        # never to produce)
        if len(sys.argv) < 3 or sys.argv[2] not in dict(BENCH_METRICS):
            print(
                f"usage: bench.py {sys.argv[1]} <metric>; metrics: "
                + ", ".join(n for n, _f in BENCH_METRICS),
                file=sys.stderr,
            )
            sys.exit(2)

    def _refuse_cpu_fallback(mode):
        # this process initializes JAX from scratch: a fail-fast
        # tunnel outage makes jax fall back to CPU SILENTLY. For --one
        # a CPU number must never be reported as a TPU metric; for
        # --prewarm a CPU run would cache executables for the wrong
        # backend AND write a breadcrumb log that reads exactly like a
        # TPU wedge, poisoning the postmortem evidence it exists to
        # produce. TPK_BENCH_EXPECT_TPU drives this guard in tests
        # (with the pool var set, sitecustomize dials the real tunnel,
        # which a test must never depend on).
        if (
            os.environ.get("PALLAS_AXON_POOL_IPS")
            or os.environ.get("TPK_BENCH_EXPECT_TPU") == "1"
        ):
            platform = jax.devices()[0].platform
            if platform not in ("tpu", "axon"):
                print(
                    f"{mode} {sys.argv[2]}: backend is {platform!r}, "
                    "not TPU - refusing to run",
                    file=sys.stderr,
                )
                sys.exit(2)

    if sys.argv[1:2] == ["--prewarm"]:
        # Per-metric compile-cache warmer (driven by tools/prewarm.py
        # --bench, the supervisor's prewarm_all step 0): the stencil3d
        # wedge (two consecutive windows, 2026-07-31) was never
        # attributed to a phase. This mode builds operands, compiles
        # BOTH R variants into the persistent cache and runs each
        # once, then exits WITHOUT timing and WITHOUT a stdout JSON
        # line — nothing a scanner could mistake for evidence.
        # Run it in a killable subprocess; the _slope stderr
        # breadcrumbs attribute any wedge to the operand, compile, or
        # execute phase (the postmortem VERDICT r4 weak #3 asked for).
        _refuse_cpu_fallback("--prewarm")
        os.environ["TPK_BENCH_PREWARM"] = "1"
        faults.enter_metric(sys.argv[2])  # no-op without a fault plan
        fn = dict(BENCH_METRICS)[sys.argv[2]]
        print(f"# prewarm: {sys.argv[2]} starting", file=sys.stderr,
              flush=True)
        fn()
        print(f"# prewarm: {sys.argv[2]} done (compiles cached)",
              file=sys.stderr, flush=True)
        sys.exit(0)
    if sys.argv[1:2] == ["--one"]:
        # child mode for main()'s per-metric subprocess isolation; the
        # SIGALRM guard stays as a soft second layer for pure-Python
        # slowness (it cannot catch a wedged PJRT call — the parent's
        # kill does that). The CPU-fallback refusal exits nonzero ->
        # parent records None ("error"); the parent's wedge probe only
        # covers the hang mode.
        _refuse_cpu_fallback("--one")
        faults.enter_metric(sys.argv[2])  # no-op without a fault plan
        fn = dict(BENCH_METRICS)[sys.argv[2]]
        # opens the operand-setup phase for the wedge-attribution
        # breadcrumbs (closed by _slope's 'entered' line)
        print(f"# one: {sys.argv[2]} starting", file=sys.stderr, flush=True)
        # jax-backed hardware stamp: this child initializes the
        # backend unconditionally in a moment, so probing is free —
        # and the metric it emits becomes attributable to the device
        # that produced it (docs/OBSERVABILITY.md §scaling). AFTER the
        # breadcrumb on purpose: if the backend init hangs on a dead
        # tunnel, the breadcrumb has already attributed the wedge to
        # this metric's startup, not to a silent pre-metric limbo.
        obs_scaling.emit_inventory("bench:one", probe=True)
        obs_metrics.inc(f"bench.measure.{sys.argv[2]}")
        with trace.span(f"measure/{sys.argv[2]}"):
            value = round(_with_timeout(fn), 2)
        print(json.dumps({"name": sys.argv[2], "value": value}))
        # the final metrics snapshot flushes via obs.metrics' atexit
        # hook — also on the Timeout/exception paths above
        sys.exit(0)
    main()
